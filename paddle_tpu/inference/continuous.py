"""Continuous batching over the paged-KV pool.

Reference capability: the block-multi-head serving path
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu) —
sequences share a page pool and join/leave the running decode batch per
step.  The round-4 GenerationServer serialized whole requests behind a
lock; this engine admits each sequence independently:

  * requests enqueue; a scheduler thread admits them whenever a running
    slot and enough pool pages are free (admission RESERVES the
    sequence's worst-case pages so mid-decode allocation can never fail
    and wedge the batch);
  * every decode step runs ALL active sequences as one batch — each at
    its own length/position (per-row rope positions, per-row page
    tables), so a long generation no longer blocks short ones behind it;
  * finished sequences retire per step (pages freed, waiter woken) and
    their slots are immediately re-admissible.

Batch shapes are bucketed to powers of two (padding rows ride on a
scratch sequence that is truncated every step) so the decode step
compiles once per bucket, not once per active-count.

Resilience layer (ISSUE 4):

  * request lifecycle — per-request deadlines (queue-wait +
    total TTL), cooperative ``cancel()`` honored at admission and
    between decode steps, and a bounded admission queue whose overflow
    raises :class:`EngineSaturated` (HTTP 429 at the server);
  * graceful drain — ``drain()`` stops new submissions, finishes
    everything already submitted, then reclaims the pool and stops the
    scheduler (``stop()`` stays the hard kill);
  * failure isolation — a failing prefill errors only its request; a
    failing decode step is retried once and then BISECTED (solo replay
    at size 1) to eject exactly the poisoned sequence(s) while the rest
    of the batch keeps decoding;
  * stall detection — an engine heartbeat registered with the comm
    watchdog (``step_timeout_s``) fires the same timeout machinery as
    a hung collective when a device step wedges;
  * deterministic fault injection — the ``paddle_tpu.testing.faults``
    sites ``prefill`` / ``decode_step`` / ``page_alloc`` are consulted
    at near-zero cost when no plan is installed.

Speculative decoding (ISSUE 6):

  * pass ``draft_model`` and the engine decodes speculatively: the
    draft proposes ``spec_tokens`` greedy tokens per active sequence in
    ONE compiled scan over its OWN PagedKVCache (pages allocated/freed
    in lockstep with the target's), then the target scores the whole
    ``[B, k+1]`` block in ONE compiled verify dispatch — accept lengths
    and the bonus token are computed on device, so the host boundary
    stays ``(batch,)`` ids + ``(batch,)`` accept counts;
  * greedy speculative decoding is EXACT (bit-identical tokens to
    target-only greedy, whatever the draft proposes); sampled requests
    ride along unaccelerated (their draft slots never match, so they
    advance exactly one fused-sampled token per step);
  * rejected suffixes roll back via page-granular length truncation on
    BOTH caches (pages stay mapped inside the admission reservation);
    draft-side failures DOWNGRADE the affected requests to plain decode
    instead of quarantining them — speculation is an optimization, so
    a broken draft must never fail a request.

Heterogeneous workloads (ISSUE 7):

  * admission and step composition are delegated to a
    :class:`~paddle_tpu.inference.scheduler.WorkloadScheduler` —
    ``submit(priority=..., tenant=...)`` routes into per-class,
    per-tenant bounded queues served by weighted deficit-round-robin
    (see scheduler.py for the policy contract);
  * **chunked prefill** — with ``prefill_chunk_tokens`` set, each
    engine iteration runs at most ~one chunk budget of prefill before
    the decode step, so a long prompt can no longer stall every
    interactive sequence's next token behind a monolithic prefill;
    chunk boundaries are position-derived (never timing-derived), KV
    pages fill incrementally through the SAME compiled context-prefill
    program the prefix cache uses, and greedy output is bit-identical
    to unchunked prefill (prefix-cache acquire still happens once, at
    admission);
  * **preemption** — a preemptible class's mid-prefill request can be
    PAUSED (slot handed to more urgent traffic) and later resumed: it
    keeps its seq id, its written pages and its reservation, and
    continues from the next chunk — it never re-prefills;
  * per-class SLO series (queue-wait / TTFT / TPOT histograms,
    admission / preemption / chunk counters) land in ``monitor``
    labeled ``cls=<class>``; ``/health`` reports queue depths and the
    active policy knobs.

Crash-consistent serving (ISSUE 8):

  * ONE replay primitive — a sequence's KV state is reconstructed by
    re-prefilling ``prompt + generated-so-far`` through the existing
    (chunked) context-prefill program.  Bit-exact for greedy AND
    sampled rows: the fused sampler's counter is ``(seed, absolute
    position)``, so a replayed draw is the original draw — and the
    already-transferred ``next_token`` is host state that survives any
    device-side loss, so the continuation is token-for-token what the
    uninterrupted run would have produced;
  * **device-failure recovery** — after a REAL donated-buffer loss the
    decoder rebuilds the pools zeroed (``PagedKVCache.generation``
    bumps); the engine detects the bump across any failed step/chunk,
    replays EVERY survivor (active, mid-prefill and preempted; draft
    pool in lockstep; prefix-cache entries re-registered with page
    refcounts restored) and only then retries/bisects — so quarantine
    ejects exactly the poisoned row for device-side failures too, not
    just host-side ones;
  * **watchdog-driven restart** — when the ``step_timeout_s``
    heartbeat fires, the watchdog's ``on_timeout`` callback flags the
    in-flight step as wedged; the engine then performs a BOUNDED
    rebuild (reset pools + survivor replay + one retry, after which
    the normal retry/bisect ladder bounds further attempts) instead of
    only incrementing ``comm_timeouts_total``;
  * **snapshot/restore** — ``snapshot()`` quiesces at a step boundary
    and serializes every in-flight request (prompt, generated ids,
    pending next token, seed, class/tenant, draft opt-in, remaining
    TTL) to a JSON-able journal; ``restore()`` resubmits each entry
    through the replay primitive (admission-path mode: the chunked
    prefill ingests ``prompt + generated`` instead of the prompt), so
    a restarted process resumes mid-stream requests exactly;
  * telemetry: ``survivor_replays_total`` / ``engine_rebuilds_total``
    counters, the ``engine_recovery_seconds`` histogram (serving MTTR)
    and ``snapshot_requests_total``.
"""
from __future__ import annotations

import math
import threading
import time
import uuid
from collections import OrderedDict, namedtuple
from typing import List, Optional

import jax
import numpy as np
from .. import monitor
from ..monitor.trace import get_tracer as _get_tracer
from ..ops.pallas.paged_attention import PagedKVCache
from ..testing import faults as _faults
from .scheduler import (DEFAULT_CLASS, PriorityClass, QueueFull,
                        WorkloadScheduler)

__all__ = [
    "ContinuousBatchingEngine", "EngineSaturated", "EngineDraining",
    "DeadlineExceeded", "RequestCancelled", "retry_after_seconds",
    "PriorityClass", "WorkloadScheduler",
]

_PAD_SEQ = "__pad__"


# fault-injection sites whose quarantine semantics are defined against
# the LEGACY per-mode dispatch granularity (one poisoned chunk fails one
# request, a decode fault bisects the batch, ...): an iteration running
# under a plan that targets any of them diverts to the legacy
# composition so chaos plans keep their documented blast radius
_ENGINE_FAULT_SITES = frozenset((
    "prefill", "prefill_chunk", "decode_step", "engine_wedge",
    "buffer_loss", "page_alloc"))
# ... EXCEPT pure pacing: a delay-kind rule on a dispatch site injects
# no failure — the unified step fires these sites itself (same sleep,
# same seq_id targeting), so benches that throttle decode to build
# batch occupancy warm the SAME programs the measured window runs.
# Delay rules on engine_wedge/buffer_loss/page_alloc still divert:
# those delays are semantic triggers (watchdog wedges, donated-buffer
# loss windows), defined against the legacy machinery.
_PACING_FAULT_SITES = frozenset(("prefill", "prefill_chunk",
                                 "decode_step"))


def _null_sampling(n: int = 1):
    """Fused-sampling args whose rows draw nothing (flags all False):
    the argmax-only program tail for dispatches whose sampled value is
    discarded — intermediate prefill chunks, draft prompt ingestion,
    and KV replay."""
    return (np.zeros(n, np.uint32), np.zeros(n, np.int32),
            np.ones(n, np.float32), np.zeros(n, bool))


class EngineSaturated(RuntimeError):
    """The bounded admission queue is full — retryable later (the
    GenerationServer maps this to HTTP 429 + Retry-After)."""


class EngineDraining(RuntimeError):
    """The engine is draining for graceful shutdown and accepts no new
    submissions (HTTP 503; in-flight requests still complete)."""


class DeadlineExceeded(RuntimeError):
    """The request's queue-wait or total TTL expired before completion
    (HTTP 504); its pages/reservation were reclaimed."""


class RequestCancelled(RuntimeError):
    """The request was cooperatively cancelled via ``cancel()``."""


class _EngineWedged(RuntimeError):
    """Internal (ISSUE 8): the comm watchdog flagged the in-flight
    compiled step as wedged (heartbeat age exceeded
    ``step_timeout_s``).  The engine treats the step's results as
    suspect: pools are rebuilt, survivors replayed, and the step
    retried — the normal retry/bisect ladder bounds a persistent
    wedge."""


# engine telemetry (ISSUE 1): the serving-side numbers the ROADMAP's
# "serve heavy traffic" goal is judged by
_queue_depth = monitor.gauge(
    "inference_queue_depth", "sequences waiting for admission")
_active_seqs = monitor.gauge(
    "inference_active_sequences", "sequences in the running decode batch")
_batch_occupancy = monitor.histogram(
    "inference_batch_occupancy", "active/max_batch fraction per decode "
    "step", buckets=tuple(i / 8 for i in range(1, 9)))
_decode_step_s = monitor.histogram(
    "decode_step_seconds", "one continuous-batching decode step")
_prefill_s = monitor.histogram(
    "prefill_seconds", "one sequence's prefill")
_tokens_total = monitor.counter(
    "generated_tokens_total", "tokens produced by the decode loop")
_ttft_s = monitor.histogram(
    "time_to_first_token_seconds", "submit -> first sampled token")
_gen_latency_s = monitor.histogram(
    "generate_latency_seconds", "submit -> sequence retirement")
# serving hot-path telemetry (ISSUE 2): prefix-cache effectiveness and
# the on-device-sampling mode flag
_prefix_lookups = monitor.counter(
    "prefix_cache_lookups_total", "admissions that consulted the prefix "
    "cache")
_prefix_hits = monitor.counter(
    "prefix_cache_hits_total", "admissions whose prompt shared a cached "
    "page-aligned prefix")
_prefix_hit_tokens = monitor.counter(
    "prefix_cache_hit_tokens_total", "prompt tokens served from cached "
    "prefix pages instead of being re-prefilled")
_sampling_on_device_g = monitor.gauge(
    "sampling_on_device", "1 when the engine samples inside the compiled "
    "step (host transfer is (batch,) ids), 0 on the host-logits path")
# resilience telemetry (ISSUE 4): failure isolation + lifecycle + the
# serving heartbeat the watchdog scans
_decode_retries = monitor.counter(
    "decode_retries_total", "decode-step re-executions after a failure "
    "(one whole-batch retry, then one per bisection probe)")
_quarantined = monitor.counter(
    "quarantined_requests_total", "requests ejected by failure "
    "isolation: failed prefill, or poisoned sequence identified by "
    "decode-step bisection")
_expired_total = monitor.counter(
    "requests_expired_total", "requests retired by deadline expiry "
    "(queue-wait or total TTL)")
_cancelled_total = monitor.counter(
    "requests_cancelled_total", "requests retired by cooperative "
    "cancel()")
_saturated_total = monitor.counter(
    "engine_saturated_total", "submissions rejected because the bounded "
    "admission queue was full")
_last_step_ts = monitor.gauge(
    "engine_last_step_timestamp_seconds", "unix time the engine last "
    "completed a prefill or decode step — the serving heartbeat")
_draining_g = monitor.gauge(
    "engine_draining", "1 while the engine is draining for graceful "
    "shutdown, else 0")
_drain_rejected = monitor.counter(
    "drain_rejected_requests_total", "queued-but-unadmitted requests "
    "failed fast by drain(reject_queued=True)")
# speculative-decoding telemetry (ISSUE 6): acceptance economics and the
# draft cache's capacity footprint
_spec_proposed = monitor.counter(
    "spec_proposed_tokens_total", "draft tokens proposed to the "
    "compiled verify step")
_spec_accepted = monitor.counter(
    "spec_accepted_tokens_total", "proposed draft tokens the target "
    "verified and accepted")
_spec_accept_len = monitor.histogram(
    "spec_accept_len", "accepted draft tokens per sequence per verify "
    "step", buckets=tuple(float(i) for i in range(9)) + (12.0, 16.0,
                                                        24.0, 32.0))
_spec_rollback = monitor.counter(
    "spec_rollback_total", "per-sequence verify outcomes that rejected "
    "a draft suffix (partial multi-token rollback on both caches)")
_spec_draft_pages = monitor.gauge(
    "spec_draft_pages", "pages pinned in the draft model's KV pool — "
    "the speculative mode's capacity cost")
_spec_draft_failures = monitor.counter(
    "spec_draft_failures_total", "draft-side prefill/propose failures "
    "that downgraded requests to plain decode")
# crash-consistency telemetry (ISSUE 8): the recovery machinery's
# footprint — replays per survivor, rebuild events, and the MTTR
# histogram the serve_bench recovery lane quotes
_survivor_replays = monitor.counter(
    "survivor_replays_total", "sequences whose KV was reconstructed by "
    "replay (re-prefill of prompt + generated-so-far) after a "
    "donated-buffer loss or watchdog-driven rebuild")
_rebuilds_total = monitor.counter(
    "engine_rebuilds_total", "pool-rebuild recovery events the engine "
    "absorbed: device-side donated-buffer losses plus watchdog-flagged "
    "wedged steps")
_recovery_s = monitor.histogram(
    "engine_recovery_seconds", "one recovery event end to end: pool "
    "rebuild + every survivor's KV replay (the serving MTTR)")
_snapshot_reqs = monitor.counter(
    "snapshot_requests_total", "in-flight requests serialized by "
    "engine.snapshot()")
# quantized-serving telemetry (ISSUE 9): the capacity lever's footprint
_quant_enabled_g = monitor.gauge(
    "quant_enabled", "1 when the engine's compiled programs run "
    "quantized weights (w8/w8a8), else 0")
_kv_quant_enabled_g = monitor.gauge(
    "kv_quant_enabled", "1 when the PagedKVCache stores int8 pages "
    "with per-slot scale pools, else 0")
_kv_quant_pool_bytes_g = monitor.gauge(
    "kv_quant_pool_bytes", "resident bytes of the KV data pages "
    "(int8 mode stores a quarter of f32 / half of bf16)")
_kv_quant_scale_bytes_g = monitor.gauge(
    "kv_quant_scale_bytes", "resident bytes of the int8 mode's "
    "per-slot scale pools (0 at full precision)")
# batched survivor replay (ISSUE 9 satellite): dispatch economics —
# fewer compiled dispatches per recovery event is the MTTR lever
_replay_dispatches = monitor.counter(
    "replay_dispatches_total", "compiled dispatches issued by survivor-"
    "KV replay (batched replay amortizes many survivors per dispatch)")
# ragged unified step (ISSUE 17): dispatch economics.  The legacy step
# composition issues one compiled dispatch per program mode per
# iteration (prefill, chunk, decode, draft propose, verify); the
# unified step folds prefill/chunk/decode/verify rows into ONE "ragged"
# dispatch, so a mixed iteration's serving cost is quoted straight off
# this counter's mode split (serve_bench's mixed-batch lane gates on it)
_dispatches_total = monitor.counter(
    "engine_dispatches_total", "compiled program dispatches issued by "
    "the serving loop, per program mode — 'ragged' is the unified "
    "single-dispatch step; 'prefill'/'chunk'/'decode'/'verify' are the "
    "legacy composition; 'draft' is the draft model's own propose/"
    "ingest dispatches (a second model: never foldable)", ("mode",))
_unified_fallbacks = monitor.counter(
    "engine_unified_fallbacks_total", "iterations where the unified "
    "ragged dispatch failed and the engine re-ran the step through the "
    "legacy multi-dispatch composition (whose retry/bisect isolation "
    "then owns the failure)")

# closed-loop overload protection (ISSUE 19): the controller's own
# series — materialized at import so existence gates (chaos_smoke) see
# them before the first overload
_decode_preempt_total = monitor.counter(
    "decode_preemptions_total", "decoding rows paused mid-decode "
    "(pages kept, next token still pending host-side) so an urgent "
    "waiter could take the slot or an interactive row could get back "
    "inside its TPOT budget; the row resumes bit-exactly through the "
    "preempt/resume path")
_brownout_level_g = monitor.gauge(
    "engine_brownout_level", "degradation ladder rung: 0 normal, "
    "1 shed least-urgent class, 2 shed two least-urgent classes, "
    "3 interactive-only (tightened deadline checks), 4 journal "
    "fsync flipped to 'os'")
_brownout_transitions = monitor.counter(
    "engine_brownout_transitions_total", "brownout ladder rung "
    "changes (escalations are immediate, de-escalations are damped "
    "by the hysteresis patience)")
_decode_preempt_total.inc(0)
_brownout_level_g.set(0)
_brownout_transitions.inc(0)

# request-level tracing (ISSUE 10): the process-wide trace buffer —
# OFF outside a monitor.start_capture() window, when every probe below
# is a single attribute read (the decode hot path must not notice it)
_tracer = _get_tracer()


def _note_quarantine(req) -> None:
    """Count a quarantine AND stamp it on the request's trace timeline
    (the chaos gate asserts a quarantined request's timeline carries
    the event) — one helper so the counter and the trace can't drift
    across the many ejection sites."""
    _quarantined.inc()
    _tracer.request_event(
        getattr(req, "request_id", None), "quarantine",
        error=(type(req.error).__name__ if req.error is not None
               else None))

#: one request's share of a speculative verify step: the bonus token
#: (ids or the logits-row escape hatch), the device-computed accept
#: length, and the draft tokens the host already knows (so accepted
#: token VALUES never cross the host boundary a second time)
_SpecRow = namedtuple("_SpecRow", ("out", "accept", "drafts"))


def _decode_p50_seconds() -> Optional[float]:
    """p50 of the process-wide ``decode_step_seconds`` histogram
    (prometheus-style upper bucket bound), or None before the engine
    has decoded anything."""
    counts = _decode_step_s.cumulative_counts()
    total = counts[-1]
    if total <= 0:
        return None
    rank = 0.5 * total
    for bound, cum in zip(_decode_step_s.buckets, counts):
        if cum >= rank:
            return bound
    return _decode_step_s.buckets[-1]


def retry_after_seconds(queue_depth: int,
                        decode_p50_s: Optional[float]) -> int:
    """Retry-After for a saturated engine: the backlog's estimated
    service time — queue depth x measured decode-step p50 — clamped to
    [1, 30] seconds (ROADMAP PR 4 follow-up c: replaces the constant
    1s).  Falls back to 1s before any step has been measured."""
    if not queue_depth or not decode_p50_s or decode_p50_s <= 0:
        return 1
    return int(min(30.0, max(1.0, math.ceil(queue_depth * decode_p50_s))))


class _Request:
    """One sequence's life in the engine."""

    def __init__(self, prompt, max_new_tokens, eos_token_id, do_sample,
                 temperature, seed, ttl_s=None, queue_timeout_s=None,
                 priority=None, tenant="default", request_id=None):
        # request-id continuity (ISSUE 10 satellite + ROADMAP crash
        # follow-up (a)): a stable, client-visible id — caller-supplied
        # or server-assigned — that survives snapshot/restore, keys the
        # bounded result cache (GET /result/<id> re-attach after a
        # restart) and names this request's trace timeline
        self.request_id = (str(request_id) if request_id
                           else f"req-{uuid.uuid4().hex[:16]}")
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.seed = int(seed) & 0xFFFFFFFF   # on-device threefry seed
        self.rng = np.random.default_rng(seed)
        self.prefix_tokens = 0               # prompt tokens shared at admit
        # heterogeneous-workload scheduling (ISSUE 7): the class/tenant
        # the scheduler queues this request under, and the chunked
        # prefill cursor (prompt tokens already resident in the cache —
        # a preempted request resumes from here, never re-prefills)
        self.priority = priority             # normalized by the scheduler
        self.tenant = str(tenant)
        self.prefill_pos = 0
        self.chunks_done = 0
        self.admitted_at: Optional[float] = None
        self._admit_plan = None          # (need, shared_tok) fit-check stash
        # crash consistency (ISSUE 8): a restored request carries the
        # full prompt + generated token sequence its prefill must make
        # KV-resident (the replay primitive's admission-path mode);
        # preempted_at/paused_total bound a paused prefill's page
        # reservation (paused_total accumulates across preempt/resume
        # cycles so re-preemption cannot reset the aging clock)
        self.replay_tokens: Optional[np.ndarray] = None
        self.preempted_at: Optional[float] = None
        self.paused_total = 0.0
        # speculative decoding (ISSUE 6): set by the engine at submit;
        # _draft_reserved tracks whether draft-pool reservation is held
        self.use_draft = False
        self._draft_reserved = False
        self.generated: List[int] = []
        self.next_token: Optional[int] = None   # sampled, not yet decoded
        self.seq_id: Optional[int] = None
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        # lifecycle (ISSUE 4): deadlines are absolute perf_counter
        # instants; the scheduler reaps at admission and between steps
        self.ttl_s = ttl_s
        self.queue_timeout_s = queue_timeout_s
        self.deadline = (None if ttl_s is None
                         else self.submitted_at + float(ttl_s))
        self.queue_deadline = (
            None if queue_timeout_s is None
            else self.submitted_at + float(queue_timeout_s))
        self._cancel = threading.Event()

    @property
    def output_ids(self) -> np.ndarray:
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    @property
    def prefill_target(self) -> np.ndarray:
        """The tokens that must be KV-resident before this request can
        decode: the prompt — or, for a restored request, prompt +
        generated-so-far (the replay primitive's admission-path mode:
        the SAME chunked context-prefill program ingests the longer
        sequence, ISSUE 8)."""
        return (self.prompt if self.replay_tokens is None
                else self.replay_tokens)

    def cancel(self) -> bool:
        """Cooperative cancel: honored before admission and between
        decode steps (an in-flight compiled step finishes first).  The
        request's pages and reservation are reclaimed when the
        scheduler reaps it; waiters get :class:`RequestCancelled`.
        Returns False if the request had already finished."""
        already_done = self.done.is_set()
        self._cancel.set()
        return not already_done

    @property
    def cancelled(self) -> bool:
        return self._cancel.is_set()

    def _lifecycle_error(self, now: float,
                         queued: bool) -> Optional[BaseException]:
        """The error this request should retire with right now, or
        None while it is still live."""
        if self._cancel.is_set():
            return RequestCancelled("request cancelled")
        if self.deadline is not None and now > self.deadline:
            return DeadlineExceeded(
                f"request exceeded its {float(self.ttl_s):.3f}s TTL")
        if queued and self.queue_deadline is not None \
                and now > self.queue_deadline:
            return DeadlineExceeded(
                f"request waited past its {float(self.queue_timeout_s):.3f}s "
                "queue-wait deadline without being admitted")
        return None

    def result(self, timeout=None, cancel_on_timeout: bool = True
               ) -> np.ndarray:
        """Wait for the generation.  On timeout the request is
        CANCELLED by default (``cancel_on_timeout=False`` keeps it
        running) so an abandoned wait does not leave the sequence
        decoding — and holding pool pages — forever."""
        if not self.done.wait(timeout):
            if cancel_on_timeout:
                self.cancel()
                raise TimeoutError(
                    "generation still running; request cancelled "
                    "(pass cancel_on_timeout=False to keep it)")
            raise TimeoutError("generation still running")
        if self.error is not None:
            raise self.error
        return self.output_ids


class ContinuousBatchingEngine:
    """Scheduler + decode loop over one shared PagedKVCache.

    ``submit`` is thread-safe and non-blocking; ``generate`` is the
    blocking batch facade with PagedGenerator's signature.

    Hot-path defaults (ISSUE 2): ``sample_on_device`` fuses greedy
    argmax + temperature sampling into the compiled step, so each
    decode step transfers (batch,) int32 ids instead of the full
    (batch, vocab) logits; ``prefix_cache`` keeps retired prompts'
    page-aligned prefix KV resident (refcounted, LRU-evicted under
    pool pressure) so a request sharing a cached prefix maps those
    pages read-only and prefills only its suffix.

    Resilience knobs (ISSUE 4): ``max_queue`` bounds EACH scheduling
    class's admission queue (overflow raises :class:`EngineSaturated`
    naming the class; per-class overrides via
    ``PriorityClass.max_queue``);
    ``default_ttl_s`` / ``default_queue_timeout_s`` set engine-wide
    deadlines each ``submit`` may override; ``step_timeout_s``
    registers a heartbeat with the comm watchdog so a wedged device
    step fires ``comm_timeouts_total`` like a hung collective.

    Speculative decoding (ISSUE 6): ``draft_model`` enables it —
    ``spec_tokens`` draft proposals per sequence per step are verified
    by ONE compiled multi-token target dispatch (exact for greedy).
    Requests opt out per-call (``submit(draft=False)``); the draft
    holds its own page pool (``draft_total_pages``, default the
    target's size) whose pages move in lockstep with the target's.

    Workload scheduling (ISSUE 7): ``prefill_chunk_tokens`` caps
    per-iteration prefill so long prompts interleave with decode;
    ``scheduler_classes`` / ``default_class`` configure the priority
    taxonomy (``submit(priority=..., tenant=...)``);
    ``min_table_pages`` pins compiled page-table widths so
    mixed-length serving stays recompile-free.

    Crash consistency (ISSUE 8): a REAL donated-buffer loss or a
    watchdog-flagged wedged step triggers a pool rebuild + bit-exact
    survivor KV replay (see the module docstring);
    :meth:`snapshot` / :meth:`restore` journal and resume in-flight
    requests across a process restart; ``preempt_resume_ttl_s`` bounds
    how long a preempted prefill may hold its page reservation (aging
    boost at half the TTL, reaped with pages reclaimed past it).

    Durability (ISSUE 13): pass ``journal`` (a
    :class:`~paddle_tpu.inference.journal.RequestJournal`) and every
    request state transition — admission, one coalesced token-emission
    record per engine step, retirement — is appended to the
    write-ahead journal by its dedicated writer thread, so a restarted
    process reconstructs the live set after a SIGKILL/OOM-kill and
    resumes every admitted request bit-exactly through the replay
    admission path (the journal generalizes :meth:`snapshot` from a
    cooperative cut to an always-current one).

    Observability (ISSUE 10): every request carries a stable
    ``request_id`` (``submit(request_id=...)`` or server-assigned,
    preserved across snapshot/restore) keying a bounded result cache
    (:meth:`result_for` — the ``GET /result/<id>`` re-attach surface)
    and, inside a ``monitor.start_capture()`` window, a per-request
    event timeline + per-engine-step records exported as chrome-trace
    JSON by ``monitor.export_chrome_trace()``.  Outside a window every
    trace probe is one attribute read — the decode hot path does not
    notice it.
    """

    def __init__(self, model, total_pages: int = 512, page_size: int = 16,
                 max_batch: int = 8, sample_on_device: bool = True,
                 prefix_cache: bool = True, max_queue: int = 256,
                 default_ttl_s: Optional[float] = None,
                 default_queue_timeout_s: Optional[float] = None,
                 step_timeout_s: Optional[float] = None,
                 draft_model=None, spec_tokens: int = 4,
                 draft_total_pages: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 scheduler_classes=None,
                 default_class: str = DEFAULT_CLASS,
                 min_table_pages: int = 1,
                 preempt_resume_ttl_s: Optional[float] = None,
                 quantize: Optional[str] = None,
                 kv_quant: Optional[str] = None,
                 replay_batch: Optional[bool] = None,
                 result_cache_size: int = 256,
                 journal=None,
                 unified_step: bool = True,
                 brownout_thresholds=None,
                 brownout_patience: int = 3,
                 decode_preempt: bool = True,
                 tpot_preempt_cooldown_s: float = 0.25,
                 tp: int = 1,
                 tp_quant_collectives: bool = False):
        self.model = model
        self.max_batch = int(max_batch)
        self.max_position = int(model.config.max_position_embeddings)
        self.sample_on_device = bool(sample_on_device)
        self.prefix_cache = bool(prefix_cache)
        self.max_queue = int(max_queue)
        self.default_ttl_s = default_ttl_s
        self.default_queue_timeout_s = default_queue_timeout_s
        self.step_timeout_s = step_timeout_s
        # heterogeneous-workload knobs (ISSUE 7): the per-step prefill
        # token budget (None = monolithic prefill, the historical
        # behavior) and the class taxonomy admission is scheduled under
        if prefill_chunk_tokens is not None \
                and int(prefill_chunk_tokens) < 1:
            raise ValueError("prefill_chunk_tokens must be >= 1 or None")
        self.prefill_chunk_tokens = (None if prefill_chunk_tokens is None
                                     else int(prefill_chunk_tokens))
        # resume-TTL for preempted prefills (ISSUE 8 satellite): a
        # paused request holds its page reservation at most this long —
        # past HALF the TTL an aging boost forces its resume ahead of
        # any queued class; past the full TTL it is reaped with pages
        # reclaimed (None keeps the historical unbounded behavior)
        self.preempt_resume_ttl_s = (
            None if preempt_resume_ttl_s is None
            else float(preempt_resume_ttl_s))
        _sampling_on_device_g.set(int(self.sample_on_device))
        # runtime mirror of the analysis auditor's recompile rules:
        # every XLA compile the decode loop triggers shows up in
        # jit_recompile_count (steady-state serving should sit at zero)
        monitor.install_compile_hooks()
        # quantized serving (ISSUE 9): ``quantize`` runs the compiled
        # programs' Linears int8 (w8 weight-only / w8a8 dynamic);
        # ``kv_quant="int8"`` stores KV pages int8 with per-slot scale
        # pools — at equal pool bytes that roughly 4x's (f32) or 2x's
        # (bf16) the pages, i.e. the concurrent sequences one chip
        # admits.  Both knobs apply to the TARGET model; a draft model
        # stays full-precision (its pool is small and its accuracy
        # directly sets the acceptance rate).
        if kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {kv_quant!r}")
        self.quantize = quantize
        self.kv_quant = kv_quant
        # batched survivor replay (ISSUE 9 satellite) is verified
        # bit-exact on CPU; on TPU its k == 0 round runs a different
        # attention kernel than the original prefill and the
        # accumulation order has NOT been re-verified (ROADMAP capture-
        # window item), so the unset default keeps the ISSUE 8
        # bit-exact recovery contract: batched everywhere but TPU.
        # Explicit True/False overrides either way.
        if replay_batch is None:
            replay_batch = jax.default_backend() != "tpu"
        self.replay_batch = bool(replay_batch)
        # tensor-parallel serving (ISSUE 20): one engine = one TP
        # replica.  ``tp > 1`` builds a 1-D ('tensor',) mesh, commits
        # the model weights to Megatron-style column/row shardings and
        # shards every KV pool on the kv-head axis, so per-chip HBM for
        # weights and pages drops by the TP degree while the engine's
        # batching/scheduling surface is unchanged — supervisors and
        # routers treat it exactly like a 1-chip replica.
        self.tp = int(tp)
        self.tp_quant_collectives = bool(tp_quant_collectives)
        if self.tp > 1:
            from ..framework.jax_compat import make_tp_mesh
            self.mesh = make_tp_mesh(self.tp)
        else:
            self.mesh = None
        self.cache = PagedKVCache.from_model(
            model, total_pages=total_pages, page_size=page_size,
            kv_dtype=kv_quant, mesh=self.mesh)
        from .paged import JittedPagedDecoder
        self._decoder = JittedPagedDecoder(
            model, min_table_pages=min_table_pages, quantize=quantize,
            mesh=self.mesh, tp_quant_collectives=self.tp_quant_collectives)
        _quant_enabled_g.set(int(quantize is not None))
        _kv_quant_enabled_g.set(int(kv_quant is not None))
        _kv_quant_pool_bytes_g.set(self.cache.kv_pool_bytes)
        _kv_quant_scale_bytes_g.set(self.cache.kv_scale_bytes)
        _replay_dispatches.inc(0)       # materialize the series
        # speculative decoding (ISSUE 6): the draft gets its own
        # decoder + page pool; proposals/verification share the target's
        # bucketing so steady-state serving stays compile-free
        self.draft_model = draft_model
        self.spec_k = int(spec_tokens)
        if draft_model is not None:
            if self.spec_k < 1:
                raise ValueError("spec_tokens must be >= 1")
            if (int(draft_model.config.vocab_size)
                    != int(model.config.vocab_size)):
                raise ValueError(
                    "draft and target models must share a vocabulary "
                    f"({draft_model.config.vocab_size} vs "
                    f"{model.config.vocab_size})")
            self._draft_decoder = JittedPagedDecoder(
                draft_model, min_table_pages=min_table_pages)
            self.draft_cache = PagedKVCache.from_model(
                draft_model,
                total_pages=(total_pages if draft_total_pages is None
                             else draft_total_pages),
                page_size=page_size)
            self._draft_max_position = int(
                draft_model.config.max_position_embeddings)
        else:
            self._draft_decoder = None
            self.draft_cache = None
            self._draft_max_position = 0
        # one scratch sequence backs every padding row of every bucket;
        # its page(s) stay allocated WHILE sequences are active
        # (the old allocate/truncate/free per padded step churned the
        # free list under the pool lock) and are released whenever the
        # engine goes idle, so an idle engine still reports a fully
        # reclaimed pool; admission arithmetic always reserves the pad
        # headroom either way.  A speculative pad row rewrites
        # spec_tokens + 1 slots per verify step, so its headroom grows
        # with k.
        pad_tokens = (self.spec_k + 1) if draft_model is not None else 1
        self._pad_pages = max(1, -(-pad_tokens // int(page_size)))
        self._reserved_pages = self._pad_pages
        self._reserved_draft_pages = self._pad_pages
        # admission queues live in the workload scheduler (per-class,
        # per-tenant DRR); the engine owns two mid-prefill lists the
        # drain/reap/fail paths must see: _prefilling (admitted, chunk
        # cursor advancing) and _preempted (paused mid-prefill, pages
        # kept, waiting for a slot to resume)
        self._sched = WorkloadScheduler(
            classes=scheduler_classes, max_queue=self.max_queue,
            default_class=default_class)
        self._active: List[_Request] = []
        self._prefilling: List[_Request] = []
        self._preempted: List[_Request] = []
        # request-id continuity (ISSUE 10 satellite): finished requests'
        # outputs/errors, keyed by request_id, bounded FIFO — a client
        # that lost its HTTP stream (timeout, server restart) re-attaches
        # via result_for() / GET /result/<id>
        self.result_cache_size = max(0, int(result_cache_size))
        self._results: "OrderedDict[str, dict]" = OrderedDict()
        # write-ahead request journal (ISSUE 13): every probe below is
        # one None check when no journal is attached.  Producers only
        # ENQUEUE (the journal's writer thread owns all I/O), so the
        # _cond hot path never waits on a disk.  _jadm/_jrows
        # accumulate the scheduler thread's per-iteration coalesced
        # step record (admitted ids + per-row token emissions); admit
        # and retire records are appended at their own sites.  The
        # engine's hard stop() path deliberately journals NOTHING —
        # "engine stopped" is process-death-adjacent, and the journal's
        # whole point is that a relaunch resumes exactly that state.
        self.journal = journal
        self._jadm: List[str] = []
        self._jrows: List[tuple] = []
        # ragged unified step (ISSUE 17): fold each iteration's
        # prefill/chunk/decode/verify rows into ONE compiled dispatch.
        # `unified_step=False` is the legacy multi-dispatch escape
        # hatch (per-mode fault-injection plans also divert an
        # iteration to it — the chaos sites fire per legacy dispatch,
        # and their quarantine semantics are defined against that
        # granularity).  `_unified_off` latches the unified path off
        # after repeated dispatch failures (lock-guarded: readers are
        # the scheduler thread, writers hold _cond); `_unified_failures`
        # and `_disp_n`/`_disp_ragged` (this iteration's dispatch count
        # and mode for the journal's step record) are scheduler-thread
        # only, like _jadm/_jrows.
        self.unified_step = bool(unified_step)
        self._unified_off = False
        self._unified_failures = 0
        self._disp_n = 0
        self._disp_ragged = False
        # closed-loop overload protection (ISSUE 19).  The brownout
        # ladder is OFF by default (None): rung thresholds are
        # queue-pressure ratios (depth / max_queue) for rungs 1..4,
        # ascending.  Escalation is immediate (overload is now);
        # de-escalation needs `brownout_patience` consecutive calm
        # iterations below the hysteresis band, and an engine going
        # idle drops straight to rung 0 (brownout is a property of
        # load, not a latch).  `decode_preempt` lets the admission loop
        # pause preemptible DECODING rows when no mid-prefill victim
        # exists; the TPOT trigger additionally preempts at full
        # occupancy when the measured step time breaches a running
        # row's `tpot_budget_s`, rate-limited by the cooldown so a
        # marginal budget cannot thrash pause/resume every iteration.
        if brownout_thresholds is not None:
            brownout_thresholds = tuple(
                float(t) for t in brownout_thresholds)
            if len(brownout_thresholds) != 4 \
                    or list(brownout_thresholds) \
                    != sorted(brownout_thresholds):
                raise ValueError(
                    "brownout_thresholds must be 4 ascending "
                    f"queue-pressure ratios, got {brownout_thresholds!r}")
        self.brownout_thresholds = brownout_thresholds
        self.brownout_patience = max(1, int(brownout_patience))
        self.decode_preempt = bool(decode_preempt)
        self.tpot_preempt_cooldown_s = float(tpot_preempt_cooldown_s)
        self._brownout = 0
        self._brownout_calm = 0         # scheduler-thread only
        self._step_ewma: Optional[float] = None   # scheduler-thread only
        self._tpot_last_preempt = 0.0   # scheduler-thread only
        self._cond = threading.Condition()
        self._stop = False
        self._draining = False
        self._next_seq = 0
        self.steps = 0                          # decode steps executed
        # crash consistency (ISSUE 8): the summed pool generation the
        # engine last reconciled (a mismatch after a failed step means
        # a donated-buffer loss zeroed survivor KV — replay required);
        # _wedged is set from the WATCHDOG thread when the heartbeat
        # fires, consumed at the next step boundary; _stepping/_
        # snap_waiters implement the snapshot() quiesce barrier
        self._pool_gen = self.cache.generation + (
            self.draft_cache.generation if self._spec else 0)
        # trace support (ISSUE 10): the last executed step's speculative
        # economics, read by the step-ring record (scheduler-thread only)
        self._last_spec = (0, 0)
        self._wedged = threading.Event()
        self._stepping = False
        self._snap_waiters = 0
        # stall detection (ISSUE 4): while a compiled step is in flight
        # this holds its start instant; the watchdog heartbeat reports
        # its age so a wedged step trips the comm timeout machinery
        self._step_started_at: Optional[float] = None
        self._hb_id: Optional[int] = None
        if step_timeout_s is not None:
            from ..distributed.watchdog import CommTaskManager
            mgr = CommTaskManager.instance()
            self._hb_id = mgr.register_heartbeat(
                "engine/decode_step", self._step_age,
                float(step_timeout_s), on_timeout=self._wedged.set)
            mgr.start()
        # journal co-location (ISSUE 19 satellite): every live engine
        # registers with the journal module so each journal's writer
        # scales its flush cadence by the number of engines sharing the
        # GIL on this host — N colocated writers each waking at the
        # configured interval steal N x the GIL share one does
        from . import journal as _journal_mod
        _journal_mod.engine_started()
        self._coloc_registered = True
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public
    @property
    def _spec(self) -> bool:
        return self.draft_model is not None

    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, do_sample: bool = False,
               temperature: float = 1.0, seed: int = 0,
               ttl_s: Optional[float] = None,
               queue_timeout_s: Optional[float] = None,
               draft: Optional[bool] = None,
               priority: Optional[str] = None,
               tenant: str = "default",
               request_id: Optional[str] = None,
               _restore: Optional[dict] = None) -> _Request:
        """``draft``: speculative-decoding opt-in for this request.
        ``None`` (default) speculates whenever the engine has a draft
        model and the request is greedy; ``False`` opts out; ``True``
        demands it (ValueError if the engine has no draft model or the
        request cannot speculate).

        ``priority`` names a scheduling class (``None`` -> the engine's
        default class; unknown names raise ValueError — a client
        mistake, not a capacity problem); ``tenant`` is a free-form
        tenant id fair-queued within the class.

        ``request_id`` (ISSUE 10): a stable client-visible id — the
        handle for ``result_for()`` re-attach and the request's trace
        timeline; auto-assigned (``req-<hex>``) when omitted, carried
        verbatim across snapshot/restore."""
        # validate the class BEFORE any capacity checks: an unknown
        # class must 400, never 429/503
        pclass = self._sched.resolve(priority)
        req = _Request(prompt, max_new_tokens, eos_token_id, do_sample,
                       temperature, seed,
                       ttl_s=self.default_ttl_s if ttl_s is None else ttl_s,
                       queue_timeout_s=(self.default_queue_timeout_s
                                        if queue_timeout_s is None
                                        else queue_timeout_s),
                       priority=pclass.name, tenant=tenant,
                       request_id=request_id)
        if _restore is not None:
            # snapshot restore (ISSUE 8): preload the journaled
            # generation state BEFORE the request becomes visible to
            # the scheduler thread — admission then prefills
            # prompt + generated through the replay primitive and the
            # journaled next token continues the stream exactly
            gen = [int(t) for t in _restore.get("generated", ())]
            if gen:
                req.generated = gen
                req.replay_tokens = np.concatenate(
                    [req.prompt, np.asarray(gen, np.int32)])
            # the journaled pending token is kept even with NO
            # generated tokens yet (snapshot cut between prefill
            # completion and the first decode step) — on the
            # host-logits path re-deriving it would draw from a fresh
            # RNG and break the journaled-next-token exactness
            nt = _restore.get("next_token")
            req.next_token = None if nt is None else int(nt)
            # deadlines come from the JOURNAL verbatim: a journaled
            # None means the original request had no (remaining)
            # deadline — it must NOT pick up this engine's defaults,
            # or a restore storm would reap the very streams the
            # journal exists to save
            ttl = _restore.get("ttl_remaining_s")
            req.ttl_s = ttl
            req.deadline = (None if ttl is None
                            else req.submitted_at + float(ttl))
            qt = _restore.get("queue_timeout_remaining_s")
            req.queue_timeout_s = qt
            req.queue_deadline = (None if qt is None
                                  else req.submitted_at + float(qt))
        total = len(req.prompt) + req.max_new_tokens
        # a verify step writes spec_k + 1 positions before rolling back,
        # so the rope table must cover the overhang for EVERY request a
        # speculative engine serves (opt-out rows ride in the same block)
        overhang = self.spec_k if self._spec else 0
        if total + overhang > self.max_position:
            # past the rope table the gather would silently clamp and
            # reuse the last angles (the scalar path raises; so do we)
            raise ValueError(
                f"prompt + max_new_tokens = {total} "
                + (f"+ speculative overhang {overhang} " if overhang
                   else "")
                + f"exceeds the model's max_position_embeddings "
                f"({self.max_position})")
        if draft and not self._spec:
            raise ValueError(
                "draft=True but the engine was built without a "
                "draft_model")
        use = self._spec and (draft is None or bool(draft))
        if use and req.do_sample:
            # acceptance-by-argmax is only exact for greedy rows;
            # sampled rows ride along unaccelerated instead of drawing
            # from the wrong distribution
            if draft:
                raise ValueError(
                    "speculative decoding is greedy-exact only; "
                    "draft=True cannot be combined with do_sample")
            use = False
        if use and total + self.spec_k > self._draft_max_position:
            if draft:
                raise ValueError(
                    f"prompt + max_new_tokens + speculative overhang = "
                    f"{total + self.spec_k} exceeds the DRAFT model's "
                    f"max_position_embeddings "
                    f"({self._draft_max_position})")
            use = False
        req.use_draft = use
        need = self._pages_for(req)
        if need > self.cache.total_pages - self._pad_pages:
            raise RuntimeError(
                f"request needs {need} pages but the pool holds "
                f"{self.cache.total_pages} total; grow total_pages")
        if req.use_draft and need > self.draft_cache.total_pages \
                - self._pad_pages:
            raise RuntimeError(
                f"request needs {need} draft-cache pages but the draft "
                f"pool holds {self.draft_cache.total_pages} total; grow "
                "draft_total_pages")
        with self._cond:
            if self._draining:
                raise EngineDraining(
                    "engine is draining or drained; not accepting new "
                    "requests")
            if self._stop:
                raise RuntimeError("engine stopped")
            if request_id is not None:
                # a pinned id may be REUSED after the original request
                # finished (deliberate resubmit overwrites the result
                # cache) but never while it is live: admitting a second
                # stream under the same id would interleave two
                # lifecycles in one trace timeline and make
                # /result/<id> race whichever finished last
                live = (self._active + self._prefilling
                        + self._preempted + self._sched.pending())
                if any(r.request_id == req.request_id for r in live):
                    raise ValueError(
                        f"request_id {req.request_id!r} is already "
                        "live; poll GET /result/<id> or pick a new id")
            # SLO-aware admission (ISSUE 19): shed a doomed arrival in
            # microseconds — BEFORE it enters the queue, holds a trace
            # timeline slot, or journals an admit record — when its
            # class's deadline budget is already blown by the projected
            # queue wait, or the brownout ladder sheds the class
            shed_after = self._shed_decision_locked(pclass)
            if shed_after is not None:
                self._sched.note_shed(pclass.name)
                _saturated_total.inc()
                _tracer.request_event(
                    req.request_id, "shed", cls=pclass.name,
                    retry_after_s=shed_after, brownout=self._brownout)
                err = EngineSaturated(
                    f"admission shed for class {pclass.name!r}: "
                    "projected queue wait exceeds its SLO budget "
                    f"(brownout level {self._brownout}); retry in "
                    f"~{shed_after}s")
                err.priority_class = pclass.name
                err.retry_after_s = shed_after
                raise err
            try:
                self._sched.push(req)
            except QueueFull as e:
                _saturated_total.inc()
                err = EngineSaturated(str(e))
                err.priority_class = e.priority_class
                raise err from None
            if self.journal is not None:
                # journal the admission BEFORE the request is visible
                # to the scheduler thread, so its step/retire records
                # can never precede the admit record in the log
                self.journal.append_admit(self._journal_entry(req))
            _queue_depth.set(len(self._sched))
            _tracer.request_event(
                req.request_id, "enqueue", cls=req.priority,
                tenant=req.tenant, prompt_tokens=len(req.prompt),
                restored=bool(_restore is not None))
            self._cond.notify_all()
        return req

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 seed: int = 0, ttl_s: Optional[float] = None,
                 draft: Optional[bool] = None,
                 priority: Optional[str] = None,
                 tenant: str = "default",
                 request_id: Optional[str] = None):
        """Blocking batch API (PagedGenerator-compatible): submits each
        row as its own sequence and eos-pads rows to a common length.
        If any row fails to submit or errors, the other rows are
        CANCELLED so a rejected batch never leaves orphan sequences
        decoding against the pool."""
        out, _reqs = self.generate_with_requests(
            input_ids, max_new_tokens=max_new_tokens,
            eos_token_id=eos_token_id, do_sample=do_sample,
            temperature=temperature, seed=seed, ttl_s=ttl_s, draft=draft,
            priority=priority, tenant=tenant, request_id=request_id)
        return out

    def generate_with_requests(self, input_ids, max_new_tokens: int = 32,
                               eos_token_id: Optional[int] = None,
                               do_sample: bool = False,
                               temperature: float = 1.0,
                               seed: int = 0,
                               ttl_s: Optional[float] = None,
                               draft: Optional[bool] = None,
                               priority: Optional[str] = None,
                               tenant: str = "default",
                               request_id: Optional[str] = None):
        """:meth:`generate` returning ``(output_ids, requests)`` so the
        HTTP server can hand the per-row ``request_id``s back to the
        client (ISSUE 10: a multi-row body's id seeds per-row ids as
        ``<id>/<row>``)."""
        ids = np.asarray(input_ids, np.int32)

        def rid(i: int) -> Optional[str]:
            if request_id is None:
                return None
            return request_id if len(ids) == 1 else f"{request_id}/{i}"

        reqs: List[_Request] = []
        try:
            for i, row in enumerate(ids):
                reqs.append(self.submit(row, max_new_tokens, eos_token_id,
                                        do_sample, temperature, seed + i,
                                        ttl_s=ttl_s, draft=draft,
                                        priority=priority, tenant=tenant,
                                        request_id=rid(i)))
            rows = [r.result() for r in reqs]
        except BaseException:
            for r in reqs:
                r.cancel()
            raise
        width = max(len(r) for r in rows)
        pad = 0 if eos_token_id is None else eos_token_id
        out = np.full((len(rows), width), pad, np.int32)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r
        return out, reqs

    @property
    def draining(self) -> bool:
        return self._draining

    def retry_after_hint(self, priority: Optional[str] = None) -> int:
        """Seconds a 429'd client should wait before retrying: the
        backlog x the measured decode-step p50 from the monitor,
        clamped to [1, 30].  With ``priority`` the backlog is the
        REQUESTING CLASS's queue depth (an interactive client behind an
        empty interactive queue is told 1s even while the batch queue
        is deep), otherwise the global depth.

        ISSUE 19 satellite: when the class carries a deadline budget
        the hint folds in the admission controller's projected-wait
        estimate — the time for the backlog to drain back UNDER the
        budget, not the time to drain it entirely — so the fleet
        router's min-Retry-After aggregation propagates truthful
        backpressure instead of a depth-only guess."""
        with self._cond:
            cls = None
            if priority is not None \
                    and priority in {c.name for c in self._sched.classes}:
                cls = self._sched.resolve(priority)
                depth = self._sched.depth(priority)
            else:
                depth = len(self._sched)
            level = self._brownout
        p50 = _decode_p50_seconds()
        hint = retry_after_seconds(depth, p50)
        if cls is not None and cls.deadline_s is not None \
                and p50 and p50 > 0:
            budget = cls.deadline_s * (0.5 if level >= 3 else 1.0)
            projected = depth * p50
            if projected > budget:
                hint = int(min(30.0, max(1.0,
                                         math.ceil(projected - budget))))
        return hint

    # ----------------------- closed-loop overload protection (ISSUE 19)
    def _shed_decision_locked(self, pclass) -> Optional[int]:
        """Why this arrival must shed, as a truthful Retry-After in
        seconds — or None to admit.  Two independent controllers:

        * the brownout ladder sheds whole classes: rung L sheds the L
          least-urgent rank bands (rung >= 3 sheds every non-top rank
          and HALVES the surviving class's deadline budget, so the
          interactive-only mode also tightens its own admission);
        * the class's ``deadline_s`` budget sheds individually doomed
          requests: projected queue wait (class depth x measured
          decode-step p50) already past the budget means the request
          would time out after holding pages — reject it now instead.
        """
        level = self._brownout
        p50 = _decode_p50_seconds()
        if level >= 1:
            ranks = sorted({c.rank for c in self._sched.classes})
            if pclass.rank > ranks[0]:
                bands = ranks[1:]
                shed = bands[len(bands) - min(level, len(bands)):]
                if level >= 3 or pclass.rank in shed:
                    depth = self._sched.depth(pclass.name)
                    return retry_after_seconds(max(1, depth), p50)
        budget = pclass.deadline_s
        if budget is None or not p50 or p50 <= 0:
            return None
        if level >= 3:
            budget *= 0.5
        projected = self._sched.depth(pclass.name) * p50
        if projected <= budget:
            return None
        return int(min(30.0, max(1.0, math.ceil(projected - budget))))

    def _set_brownout_locked(self, level: int, pressure: float) -> None:
        if level == self._brownout:
            return
        prev, self._brownout = self._brownout, level
        _brownout_level_g.set(level)
        _brownout_transitions.inc()
        _tracer.request_event(None, "brownout", level=level, prev=prev,
                              pressure=round(pressure, 4))
        if self.journal is not None:
            # the last rung trades the journal's configured durability
            # for throughput: fsync policy flips to "os" (explicit,
            # reversible — unlike the watchdog's sticky degrade())
            if level >= 4:
                self.journal.set_policy("os")
            elif prev >= 4:
                self.journal.set_policy(self.journal.fsync_policy)

    def _update_brownout_locked(self) -> None:
        """One control-loop evaluation, each scheduler iteration.
        Pressure is the max of queue-depth ratio and the urgent class's
        SLO-attainment deficit; rungs escalate immediately and
        de-escalate only after `brownout_patience` calm iterations
        below HALF the rung's threshold (hysteresis, so a workload
        hovering at a threshold cannot thrash the ladder)."""
        th = self.brownout_thresholds
        if th is None:
            return
        ratio = len(self._sched) / float(max(1, self.max_queue))
        att = self._sched.urgent_attainment()
        pressure = ratio if att is None else max(ratio, 1.0 - att)
        level = self._brownout
        if level < 4 and pressure >= th[level]:
            self._brownout_calm = 0
            self._set_brownout_locked(level + 1, pressure)
            return
        if level > 0 and pressure < 0.5 * th[level - 1]:
            self._brownout_calm += 1
            if self._brownout_calm >= self.brownout_patience:
                self._brownout_calm = 0
                self._set_brownout_locked(level - 1, pressure)
        else:
            self._brownout_calm = 0

    # ------------------------------------- write-ahead journal (ISSUE 13)
    @staticmethod
    def _entry_fields(r) -> dict:
        """The request fields BOTH persistence formats — the
        cooperative snapshot entry and the write-ahead journal's admit
        record — serialize identically.  One builder, so a field added
        to the request can never restore on one recovery path and be
        silently dropped on the other (the formats differ only in how
        they carry generation state and deadlines)."""
        return {
            # the stable client-visible id survives the restart — a
            # client holding it re-attaches via GET /result/<id> on
            # the restored process (ISSUE 10)
            "request_id": r.request_id,
            "max_new_tokens": r.max_new_tokens,
            "eos_token_id": (None if r.eos_token_id is None
                             else int(r.eos_token_id)),
            "do_sample": r.do_sample,
            "temperature": r.temperature,
            "seed": r.seed,
            "priority": r.priority,
            "tenant": r.tenant,
            "draft": bool(r.use_draft),
        }

    def _journal_entry(self, req) -> dict:
        """The admit record's payload: the FULL request state in the
        snapshot-entry shape (a restored request carries its generated
        tokens + pending next token, making journal replay idempotent
        by request_id), with deadlines converted to absolute WALL-CLOCK
        instants — a perf_counter deadline is meaningless in the next
        process, and the recovery scan converts back to the
        remaining-seconds fields restore() takes verbatim."""
        now_p = time.perf_counter()
        now_w = time.time()

        def wall(d):
            return None if d is None else now_w + (d - now_p)

        return {
            **self._entry_fields(req),
            "prompt": req.prompt,            # np array; writer encodes
            "generated": list(req.generated),
            "next_token": (None if req.next_token is None
                           else int(req.next_token)),
            "deadline_unix": wall(req.deadline),
            "queue_deadline_unix": wall(req.queue_deadline),
        }

    def _journal_retire(self, req) -> None:
        if self.journal is None:
            return
        why = ("done" if req.error is None
               else type(req.error).__name__)
        self.journal.append_retire(req.request_id, why=why)

    def _journal_pages(self, req, event: str, n_tokens: int) -> None:
        """Page-provenance record (ISSUE 14 satellite): the page-
        aligned prefix ``req`` shares with the prefix cache — its
        replica-local page indices plus the stable content key.
        Failover groups the migrating live set by that key (sharers
        land together, the destination's prefix index warms once); a
        disaggregated decode tier re-attaches transported pages by it
        (the ROADMAP slice this record type exists for)."""
        if self.journal is None:
            return
        ps = self.cache.page_size
        n = (int(n_tokens) // ps) * ps
        if n <= 0:
            return
        pages = self.cache._seq_pages.get(req.seq_id, [])[:n // ps]
        self.journal.append_pages(
            req.request_id, event, n, pages,
            self.cache.prefix_key_hex(req.prompt, n))

    def _journal_flush_step(self) -> None:
        """Scheduler thread, end of one loop iteration: ONE coalesced
        step record — the ids admitted to a slot plus every surviving
        row's (tokens appended, new pending next_token) — written off
        the hot path by the journal's writer thread."""
        if self.journal is not None and (self._jadm or self._jrows):
            self.journal.append_step(
                self._jadm, self._jrows, dispatches=self._disp_n,
                mode=(("ragged" if self._disp_ragged else "legacy")
                      if self._disp_n else None))
        self._jadm = []
        self._jrows = []
        self._disp_n = 0
        self._disp_ragged = False

    def _count_dispatch(self, mode: str) -> None:
        """Scheduler thread: one compiled serving dispatch ATTEMPT —
        the per-mode fleet counter plus this iteration's accumulator
        for the journal's step record (retry/bisect probes count again:
        dispatches issued IS the cost being quoted)."""
        _dispatches_total.inc(mode=mode)
        self._disp_n += 1
        if mode == "ragged":
            self._disp_ragged = True

    # ---------------------------------------- request-id surface (ISSUE 10)
    def _cache_result_locked(self, req) -> None:
        """Caller holds ``self._cond``.  Record a finished request's
        outcome in the bounded result cache so a detached client can
        re-attach by id (``GET /result/<id>``) — including after a
        snapshot/restore, where the journaled id is carried verbatim."""
        if not self.result_cache_size:
            return
        if req.error is None:
            entry = {"request_id": req.request_id, "status": "done",
                     "output_ids": [int(t) for t in req.output_ids],
                     "new_tokens": len(req.generated)}
        else:
            entry = {"request_id": req.request_id, "status": "error",
                     "error": str(req.error),
                     "error_type": type(req.error).__name__}
        self._results[req.request_id] = entry
        self._results.move_to_end(req.request_id)
        while len(self._results) > self.result_cache_size:
            self._results.popitem(last=False)

    def result_for(self, request_id: str) -> Optional[dict]:
        """The cached outcome for ``request_id`` — ``status`` is
        ``done`` (with ``output_ids``) or ``error`` once finished,
        ``pending`` while queued/decoding, None for an id this engine
        has never seen (or one evicted from the bounded cache)."""
        with self._cond:
            hit = self._results.get(request_id)
            if hit is not None:
                return dict(hit)
            live = (self._active + self._prefilling + self._preempted
                    + self._sched.pending())
            for r in live:
                if r.request_id == request_id:
                    return {"request_id": request_id, "status": "pending",
                            "generated_tokens": len(r.generated)}
        return None

    def scheduler_info(self) -> dict:
        """JSON-able scheduling state for ``/health``: the active
        policy knobs and per-class/per-tenant queue depths."""
        with self._cond:
            return {
                "prefill_chunk_tokens": self.prefill_chunk_tokens,
                "default_class": self._sched.default_class,
                "classes": self._sched.policy(),
                "tenants_queued": self._sched.tenant_depths(),
                "prefilling": len(self._prefilling),
                "preempted": len(self._preempted),
                # closed-loop overload state (ISSUE 19): the ladder
                # rung and whether the controllers are armed — the
                # fleet autoscaler reads these off /health
                "brownout_level": self._brownout,
                "brownout_enabled": self.brownout_thresholds is not None,
                "decode_preempt": self.decode_preempt,
            }

    # ------------------------------------------------- snapshot/restore
    def snapshot(self) -> dict:
        """Serialize every in-flight request to a JSON-able journal
        (ISSUE 8 tentpole, consumer 3).  Quiesces first: waits for the
        in-flight chunk/decode batch to finish so (generated,
        next_token) is a consistent between-steps cut — the journal's
        ``next_token`` is the already-transferred host-side sample, so
        a restore continues each stream token-for-token.  Safe to call
        while draining (SIGTERM snapshot-then-drain) or on an idle
        engine (empty journal)."""
        with self._cond:
            self._snap_waiters += 1
            try:
                while self._stepping and not self._stop:
                    self._cond.wait(0.1)
                # under the lock: only shallow snapshots of the mutable
                # state (generated grows once the loop resumes; prompt
                # is written once at submit).  The O(total tokens) JSON
                # conversion below runs with the lock RELEASED so a
                # deep journal never stalls submission or the loop
                now = time.perf_counter()
                # in-flight streams FIRST: restore() resubmits in
                # journal order, so if the journal saturates the
                # restoring engine's bounded queues it is never-started
                # queued work that gets dropped — not the mid-stream
                # generations the journal exists to save
                cuts = [(r, r.prompt, list(r.generated), r.next_token)
                        for r in (list(self._active)
                                  + list(self._prefilling)
                                  + list(self._preempted)
                                  + self._sched.pending())
                        if not r.done.is_set() and not r.cancelled]
            finally:
                self._snap_waiters -= 1
                self._cond.notify_all()
        entries = []
        for r, prompt, generated, next_token in cuts:
            entries.append({
                **self._entry_fields(r),
                "prompt": [int(t) for t in prompt],
                "generated": [int(t) for t in generated],
                "next_token": (None if next_token is None
                               else int(next_token)),
                "ttl_remaining_s": (
                    None if r.deadline is None
                    else max(1e-3, r.deadline - now)),
                # a request that was ALREADY admitted satisfied its
                # queue-wait contract — re-imposing the (likely spent)
                # deadline on the restore queue would reap exactly the
                # long-running streams the journal exists to save
                "queue_timeout_remaining_s": (
                    None if r.queue_deadline is None
                    or r.admitted_at is not None
                    else max(1e-3, r.queue_deadline - now)),
            })
        _snapshot_reqs.inc(len(entries))
        return {"version": 1, "requests": entries}

    def restore(self, snapshot: dict, strict: bool = True
                ) -> List[_Request]:
        """Resubmit a :meth:`snapshot` journal onto THIS engine.  Each
        entry flows through normal admission; entries with generated
        tokens carry them as ``replay_tokens`` so the chunked
        context-prefill program reconstructs their KV bit-exactly and
        the journaled next token continues the stream (ISSUE 8).
        ``strict=False`` skips entries the engine rejects (unknown
        class, full queue) with a warning instead of raising — the
        restarted-server path, where one unplaceable request must not
        abort the whole resume.  Returns the new request handles.

        Exactness caveat: sampled (``do_sample``) rows resume
        bit-exactly on the default on-device sampler, whose draws are
        keyed by (seed, absolute position).  On the
        ``sample_on_device=False`` host-logits escape hatch a sampled
        row's host RNG stream position is not journaled — its already-
        generated tokens and journaled next token are exact, but
        draws after that come from a freshly seeded RNG (greedy rows
        are exact on both paths)."""
        import warnings
        out: List[_Request] = []
        for e in snapshot.get("requests", ()):
            try:
                out.append(self.submit(
                    np.asarray(e["prompt"], np.int32),
                    max_new_tokens=int(e.get("max_new_tokens", 32)),
                    eos_token_id=e.get("eos_token_id"),
                    do_sample=bool(e.get("do_sample", False)),
                    temperature=float(e.get("temperature", 1.0)),
                    seed=int(e.get("seed", 0)),
                    # deadlines are taken verbatim from the journal by
                    # the _restore branch (incl. "no deadline"), never
                    # from this engine's defaults
                    # None lets the restored engine speculate when IT
                    # can (a journal from a drafted engine restores
                    # cleanly onto a draft-free one); False preserves
                    # an explicit opt-out
                    draft=None if e.get("draft") else False,
                    priority=e.get("priority"),
                    tenant=e.get("tenant", "default"),
                    request_id=e.get("request_id"),
                    _restore=e))
            except BaseException as exc:  # noqa: BLE001 — per-entry
                if strict:
                    raise
                warnings.warn(
                    f"snapshot restore skipped one request: {exc!r}")
        return out

    def stop_admissions(self) -> None:
        """Synchronously flip the draining flag (``drain()`` sets it
        again, idempotently).  The server's SIGTERM path calls this
        BEFORE taking the crash-floor snapshot: ``begin_drain`` only
        spawns the drain thread, so without this a request admitted in
        the spawn-to-flag window would be journal-invisible (ISSUE 8)."""
        with self._cond:
            self._draining = True
            _draining_g.set(1)
            self._cond.notify_all()

    def drain(self, timeout: Optional[float] = None,
              reject_queued: bool = False) -> bool:
        """Graceful shutdown: stop accepting NEW submissions, let every
        already-submitted request (queued and active) run to
        completion, then stop the scheduler thread — the pool reclaims
        to idle as the last sequence retires.  Returns True when fully
        drained; False if ``timeout`` elapsed first (the engine keeps
        draining — call again, or escalate to ``stop()``).

        ``reject_queued=True`` is the hard-preemption fast path
        (ROADMAP PR 4 follow-up b): queued-but-unadmitted requests fail
        fast with :class:`EngineDraining` — they hold no pages, so
        rejection is free — while admitted work still runs to
        completion within the (shorter) deadline."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        rejected: List[_Request] = []
        with self._cond:
            self._draining = True
            _draining_g.set(1)
            if reject_queued and len(self._sched):
                rejected = self._sched.pop_all()
                for r in rejected:
                    r.error = EngineDraining(
                        "engine draining: request rejected before "
                        "admission (reject_queued fast path)")
                    self._cache_result_locked(r)
                    self._journal_retire(r)
                _queue_depth.set(0)
                _drain_rejected.inc(len(rejected))
            self._cond.notify_all()
        for r in rejected:
            r.done.set()
        with self._cond:
            while len(self._sched) or self._active or self._prefilling \
                    or self._preempted:
                if self._stop:
                    # a concurrent hard stop() preempted the drain: the
                    # remaining requests were ERRORED, not completed —
                    # never report that as a successful drain
                    return False
                wait = 0.5
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                self._cond.wait(wait)
        self.stop()
        _draining_g.set(0)
        return True

    def stop(self):
        """Hard stop: errors whatever is still queued/active.  Use
        :meth:`drain` for the graceful path."""
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
        if getattr(self, "_coloc_registered", False):
            self._coloc_registered = False
            from . import journal as _journal_mod
            _journal_mod.engine_stopped()
        if self._hb_id is not None:
            from ..distributed.watchdog import CommTaskManager
            CommTaskManager.instance().unregister_heartbeat(self._hb_id)
            self._hb_id = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ---------------------------------------------------------- scheduler
    def _step_age(self) -> Optional[float]:
        """Watchdog heartbeat probe: seconds the current compiled step
        has been in flight, or None while idle (never flagged)."""
        t0 = self._step_started_at
        return None if t0 is None else time.monotonic() - t0

    def _pages_for(self, req) -> int:
        ps = self.cache.page_size
        total = len(req.prompt) + req.max_new_tokens
        if self._spec:
            # a verify step writes spec_k + 1 tokens from length
            # <= prompt + max_new - 1 before rolling back, so the
            # worst-case footprint carries a spec_k-token overhang (the
            # draft pool's propose scan peaks at the same bound)
            total += self.spec_k
        return -(-total // ps)

    def _free_pads_locked(self) -> None:
        """Caller holds ``self._cond`` (or the engine is single-threaded
        at the call site).  Release the pad scratch page(s) on every
        pool so an idle engine reports fully reclaimed capacity."""
        self.cache.free(_PAD_SEQ)
        if self._spec:
            self.draft_cache.free(_PAD_SEQ)
            _spec_draft_pages.set(self.draft_cache.pinned_pages)

    def _reap_locked(self) -> List[_Request]:
        """Caller holds ``self._cond``.  Retire queued and active
        requests that were cancelled or whose deadline passed — their
        pages and reservations are reclaimed here, so an abandoned
        request can never hold pool capacity past its TTL.  Returns the
        reaped requests; the caller sets their ``done`` events outside
        the lock."""
        now = time.perf_counter()
        out: List[_Request] = []
        for r in self._sched.reap(now):
            r.error = r._lifecycle_error(now, queued=True)
            self._count_lifecycle(r)
            self._cache_result_locked(r)
            self._journal_retire(r)
            _tracer.request_event(r.request_id, "retire", ok=False)
            out.append(r)
        if out:
            _queue_depth.set(len(self._sched))
        # mid-prefill requests (chunking spans iterations) and paused
        # preempted requests hold pages: reap them too, so a cancelled
        # or expired request never parks capacity in either list
        for lst_name in ("_prefilling", "_preempted"):
            lst = getattr(self, lst_name)
            if not lst:
                continue
            keep: List[_Request] = []
            for r in lst:
                err = r._lifecycle_error(now, queued=False)
                if err is None and lst_name == "_preempted":
                    # resume-TTL (ISSUE 8 satellite): a paused prefill
                    # may hold its page reservation at most
                    # preempt_resume_ttl_s — past that it is reaped
                    # with pages reclaimed, never parked forever
                    err = self._preempt_expired_error(r, now)
                if err is None:
                    keep.append(r)
                else:
                    r.error = err
                    self._count_lifecycle(r)
                    self._retire_locked(r)
                    out.append(r)
            setattr(self, lst_name, keep)
        if self._active:
            still: List[_Request] = []
            for r in self._active:
                err = r._lifecycle_error(now, queued=False)
                if err is None:
                    still.append(r)
                else:
                    r.error = err
                    self._count_lifecycle(r)
                    self._retire_locked(r)
                    out.append(r)
            self._active = still
            if not still:
                # everything reaped: the pad scratch page goes back too
                self._free_pads_locked()
        if out:
            self._cond.notify_all()
        return out

    @staticmethod
    def _count_lifecycle(req) -> None:
        if isinstance(req.error, RequestCancelled):
            _cancelled_total.inc()
            _tracer.request_event(req.request_id, "cancel")
        else:
            _expired_total.inc()
            _tracer.request_event(req.request_id, "expire")

    @staticmethod
    def _pause_age(r, now: Optional[float] = None) -> float:
        """Total time this request has spent preempted — the CURRENT
        pause plus every earlier preempt/resume cycle, so thrashing
        re-preemption can never reset the aging/reap clock."""
        age = r.paused_total
        if r.preempted_at is not None:
            age += (time.perf_counter() if now is None else now) \
                - r.preempted_at
        return age

    def _preempt_expired_error(self, r,
                               now: float) -> Optional[BaseException]:
        """Caller holds ``self._cond``.  The reap error for a preempted
        prefill that exhausted its resume TTL, or None while it may
        still be resumed (or no TTL is configured)."""
        ttl = self.preempt_resume_ttl_s
        if ttl is None or self._pause_age(r, now) <= ttl:
            return None
        self._sched.note_preempt_expired(r)
        return DeadlineExceeded(
            f"preempted prefill spent more than its {ttl:.3f}s resume "
            "TTL paused without a slot freeing up")

    def _preempt_rank_locked(self, r) -> int:
        """Caller holds ``self._cond``.  A request's EFFECTIVE rank
        for preemption decisions: its class rank — or, once it has
        spent half the resume TTL paused, an aging boost (rank -1)
        that outranks every queued class, so a slot that frees is
        forced to the aged request (and, symmetrically, an aged
        resumed prefill can no longer be picked as a preemption
        victim) instead of fresh urgent traffic starving it all the
        way to the reap bound."""
        ttl = self.preempt_resume_ttl_s
        if ttl is not None and self._pause_age(r) >= 0.5 * ttl:
            return -1
        return self._sched.class_of(r).rank

    def _admission_cost_locked(self, req) -> Optional[int]:
        """Caller holds ``self._cond``.  PURE fit check: the pages this
        request's admission would newly reserve (its DRR cost), or None
        when it does not fit right now.  A prompt whose prefix is
        already cached reserves only what the pool must newly provide:
        the un-shared pages plus whichever shared pages were not
        already pinned by another live sharer — shared pages are
        counted once across the engine, not once per sharer."""
        shared_tok, newly_pinned = (
            self.cache.probe_prefix(req.prompt) if self.prefix_cache
            else (0, 0))
        need = (self._pages_for(req)
                - shared_tok // self.cache.page_size + newly_pinned)
        if self._reserved_pages + need > self.cache.total_pages:
            return None
        # the draft pool reserves the full worst case too (no prefix
        # sharing there — the draft always prefills whole prompts);
        # both pools must fit or neither is reserved
        dneed = self._pages_for(req) if req.use_draft else 0
        if dneed and self._reserved_draft_pages + dneed \
                > self.draft_cache.total_pages:
            return None
        # stash the plan for _finalize_admission_locked: nothing can
        # mutate pool state between this check and the commit (same
        # lock hold), so the winner's prefix hash walk is not repeated
        req._admit_plan = (need, shared_tok)
        return max(1, need)

    def _finalize_admission_locked(self, req) -> None:
        """Caller holds ``self._cond``.  Commit an admission the cost
        check just approved: RESERVE worst-case pages (prompt + full
        max_new_tokens) so decode-time allocate() can never exhaust the
        pool, assign the seq id, and ACQUIRE any cached prefix (pinning
        the shared pages against eviction).  Prefill itself runs
        outside the lock — submit() must never wait on device work."""
        need, shared_tok = req._admit_plan
        req._admit_plan = None
        self._reserved_pages += need
        if req.use_draft:
            self._reserved_draft_pages += self._pages_for(req)
            req._draft_reserved = True
        req.seq_id = self._next_seq
        self._next_seq += 1
        if shared_tok:
            got = self.cache.acquire_prefix(req.seq_id, req.prompt)
            assert got == shared_tok   # nothing ran between probe/acquire
            req.prefix_tokens = got
        req.prefill_pos = req.prefix_tokens
        req.admitted_at = time.perf_counter()
        self._sched.note_admitted(req, req.admitted_at)
        if self.journal is not None:
            # the admitted marker drops the (satisfied) queue-wait
            # deadline on recovery — the PR 8 snapshot convention
            self._jadm.append(req.request_id)
            if req.prefix_tokens:
                # page provenance (ISSUE 14 satellite): which cached
                # prefix pages this admission mapped read-only — the
                # content key is what survives a replica boundary
                self._journal_pages(req, "acquired", req.prefix_tokens)
        _tracer.request_event(
            req.request_id, "admitted", cls=req.priority,
            seq_id=req.seq_id, prefix_tokens=req.prefix_tokens,
            queue_wait_s=round(req.admitted_at - req.submitted_at, 6))

    def _tpot_parked_locked(self, r) -> bool:
        """Caller holds ``self._cond``.  True while a row parked by the
        TPOT trigger must STAY parked: some active row's TPOT budget is
        still breached by the measured step time.  The aging boost
        (half the resume TTL) overrides, so TPOT parking can never
        starve a row past the reservation-bound contract; once no
        active row is breaching (the interactive burst retired, or the
        smaller batch brought the step time back under budget) the row
        resumes through the ordinary path."""
        if not getattr(r, "_tpot_parked", False):
            return False
        if self._preempt_rank_locked(r) < self._sched.class_of(r).rank:
            return False                       # aging boost won
        ewma = self._step_ewma
        if ewma is None:
            return False
        for a in self._active:
            budget = self._sched.class_of(a).tpot_budget_s
            if budget is not None and ewma > budget:
                return True
        return False

    def _best_preempted_locked(self) -> Optional[_Request]:
        """Caller holds ``self._cond``.  The paused request that should
        resume first: most urgent EFFECTIVE class (aging boost
        included), then preemption order.  Rows the TPOT trigger parked
        stay invisible while the budget breach that parked them
        persists — resuming one into the still-too-slow batch would
        undo the preemption the very next iteration."""
        cands = [r for r in self._preempted
                 if not self._tpot_parked_locked(r)]
        if not cands:
            return None
        return min(cands,
                   key=lambda r: (self._preempt_rank_locked(r),
                                  self._preempted.index(r)))

    def _preemption_victim_locked(self, rank: int) -> Optional[_Request]:
        """Caller holds ``self._cond``.  The request to pause so a
        rank-``rank`` request can take its slot: the LEAST urgent
        preemptible prefilling request strictly outranked by the
        waiter, preferring the least prefill progress (cheapest pause).
        EFFECTIVE rank, so an aging-boosted resumed prefill is immune
        to re-preemption — a forced resume must stick.

        With ``decode_preempt`` (ISSUE 19) and no mid-prefill victim,
        the search extends to DECODING rows: the least urgent
        preemptible active row is paused mid-decode — pages kept, its
        pending ``next_token`` still host-side — and re-enters through
        the same resume path, so batch-class rows squatting decode
        slots can no longer wall off urgent admissions."""
        victims = [r for r in self._prefilling
                   if self._sched.class_of(r).preemptible
                   and self._preempt_rank_locked(r) > rank]
        if victims:
            return max(victims,
                       key=lambda r: (self._sched.class_of(r).rank,
                                      -r.prefill_pos))
        if not self.decode_preempt:
            return None
        victims = [r for r in self._active
                   if self._sched.class_of(r).preemptible
                   and self._preempt_rank_locked(r) > rank]
        if not victims:
            return None
        return max(victims,
                   key=lambda r: (self._sched.class_of(r).rank,
                                  -len(r.generated)))

    def _pause_locked(self, victim, for_rank: int) -> None:
        """Caller holds ``self._cond``.  Move a preemption victim —
        mid-prefill or mid-decode — onto the paused list (seq id,
        pages and reservation all kept)."""
        if victim in self._prefilling:
            self._prefilling.remove(victim)
        else:
            self._active.remove(victim)
            _decode_preempt_total.inc()
        victim.preempted_at = time.perf_counter()
        self._preempted.append(victim)
        self._sched.note_preempted(victim)
        _tracer.request_event(
            victim.request_id, "preempt", for_rank=for_rank,
            prefill_pos=victim.prefill_pos,
            decoded=len(victim.generated))

    def _resume_locked(self, pre) -> None:
        """Caller holds ``self._cond``.  Un-pause a preempted request:
        its pause time banks into ``paused_total`` (the aging/reap
        clock survives the resume) and chunking continues from
        ``prefill_pos`` — it never re-prefills.  A row preempted
        MID-DECODE (prefill complete, next token pending host-side)
        rejoins the decode batch directly: its first token was already
        emitted, so routing it through _prefilling would strand it —
        the chunk planner has no work for a finished prefill."""
        self._preempted.remove(pre)
        if pre.preempted_at is not None:
            pre.paused_total += time.perf_counter() - pre.preempted_at
            pre.preempted_at = None
        pre._tpot_parked = False
        if pre.first_token_at is not None \
                and pre.prefill_pos >= len(pre.prefill_target):
            self._active.append(pre)
        else:
            self._prefilling.append(pre)
        self._sched.note_resumed(pre)
        _tracer.request_event(pre.request_id, "resume",
                              prefill_pos=pre.prefill_pos,
                              decoded=len(pre.generated),
                              paused_s=round(pre.paused_total, 6))

    def _tpot_preempt_locked(self) -> None:
        """Caller holds ``self._cond``.  The TPOT feedback loop
        (ISSUE 19): at full occupancy, when the engine's measured
        iteration time (EWMA over decode-bearing steps — for an active
        row, one iteration IS one output token) breaches a running
        row's ``tpot_budget_s``, pause the least-urgent preemptible
        DECODING row so the smaller batch steps faster.  Rate-limited
        by ``tpot_preempt_cooldown_s``; the parked row stays invisible
        to resume while the breach persists (see _tpot_parked_locked)
        and its pause time still accrues toward the aging/reap
        clocks."""
        if not self.decode_preempt or not self._active:
            return
        if len(self._active) + len(self._prefilling) < self.max_batch:
            return
        ewma = self._step_ewma
        if ewma is None:
            return
        now = time.perf_counter()
        if now - self._tpot_last_preempt < self.tpot_preempt_cooldown_s:
            return
        breached = [r for r in self._active
                    if self._sched.class_of(r).tpot_budget_s is not None
                    and ewma > self._sched.class_of(r).tpot_budget_s]
        if not breached:
            return
        urgent = min(self._sched.class_of(r).rank for r in breached)
        victims = [r for r in self._active
                   if self._sched.class_of(r).preemptible
                   and self._preempt_rank_locked(r) > urgent]
        if not victims:
            return
        victim = max(victims,
                     key=lambda r: (self._sched.class_of(r).rank,
                                    -len(r.generated)))
        self._pause_locked(victim, urgent)
        victim._tpot_parked = True
        self._tpot_last_preempt = now

    def _admit_locked(self) -> None:
        """Caller holds ``self._cond``.  Fill free slots from (a) paused
        preempted requests — they resume for free, their pages are
        already reserved — and (b) the workload scheduler's queues in
        weighted-DRR order; when every slot is held and a MORE URGENT
        class is waiting, pause a preemptible mid-prefill request and
        hand its slot over (the tentpole preemption path: the victim
        keeps seq id, pages and reservation, and resumes later).
        Under SUSTAINED higher-priority load a preemptible request
        stays paused (that is the priority contract) while holding its
        reservation — bound the pause with a request TTL if that
        matters; the ROADMAP carries resume-aging as a follow-up."""
        pending_rank = None     # rank a preemption just freed a slot for
        while True:
            slots = (self.max_batch - len(self._active)
                     - len(self._prefilling))
            qrank = self._sched.min_waiting_rank()
            pre = self._best_preempted_locked()
            if slots <= 0:
                if qrank is None:
                    break
                victim = self._preemption_victim_locked(qrank)
                head = self._sched.peek_urgent()
                if victim is None or head is None \
                        or self._admission_cost_locked(head) is None:
                    break
                self._pause_locked(victim, qrank)
                pending_rank = qrank
                continue
            if pending_rank is None and pre is not None and (
                    qrank is None
                    or self._preempt_rank_locked(pre) <= qrank):
                self._resume_locked(pre)
                continue
            # a slot bought with a preemption belongs to the rank it
            # was preempted for: a less urgent class's banked DRR
            # deficit must not snatch it (that would pause one batch
            # prefill just to start another)
            req = self._sched.pop_next(self._admission_cost_locked,
                                       max_rank=pending_rank)
            pending_rank = None
            if req is None:
                if pre is not None:
                    self._resume_locked(pre)
                    continue
                break
            self._finalize_admission_locked(req)
            self._prefilling.append(req)
        _queue_depth.set(len(self._sched))

    def _plan_chunks_locked(self) -> List:
        """Caller holds ``self._cond``.  (request, n_tokens) prefill
        work for THIS iteration: most urgent classes first, bounded by
        the per-step chunk budget.  A request's chunk is never split to
        fit leftover budget — chunk sizes are position-derived (full
        ``prefill_chunk_tokens`` or the prompt's tail), so the compiled
        bucket shapes a workload needs are deterministic, never
        timing-dependent.  Requests whose chunk the budget gave to a
        MORE URGENT class are counted as deferred (the soft half of
        preemption; the slot pause above is the hard half — same-class
        queueing is not a deferral)."""
        if not self._prefilling:
            return []
        order = sorted(
            range(len(self._prefilling)),
            key=lambda i: (self._sched.class_of(
                self._prefilling[i]).rank, i))
        chunk = self.prefill_chunk_tokens
        plan: List = []
        budget = chunk if chunk is not None else None
        best_served_rank: Optional[int] = None
        for i in order:
            req = self._prefilling[i]
            remaining = len(req.prefill_target) - req.prefill_pos
            if remaining <= 0:     # defensive: completion moves it out
                continue
            if budget is None:
                plan.append((req, remaining))
                continue
            if budget <= 0:
                # the deferral metric means PRIORITY pressure: count it
                # only when the budget actually went to a more urgent
                # class, not when same-class peers simply queued up
                rank = self._sched.class_of(req).rank
                if best_served_rank is not None \
                        and rank > best_served_rank:
                    self._sched.note_chunk_deferred(req)
                continue
            n = min(remaining, chunk)
            plan.append((req, n))
            rank = self._sched.class_of(req).rank
            if best_served_rank is None or rank < best_served_rank:
                best_served_rank = rank
            budget -= n
        return plan

    def _sampling_for(self, reqs, ctrs):
        """(seeds, ctrs, temps, flags) arrays for the fused on-device
        sampler, padded to ``len(ctrs)`` rows (pad rows draw nothing:
        flags False).  ``ctrs`` is each row's absolute token position —
        the replay-stable per-draw counter."""
        n = len(ctrs)
        seeds = np.zeros(n, np.uint32)
        temps = np.ones(n, np.float32)
        flags = np.zeros(n, bool)
        for i, r in enumerate(reqs):
            seeds[i] = r.seed
            temps[i] = max(r.temperature, 1e-6)
            flags[i] = r.do_sample
        return seeds, np.asarray(ctrs, np.int32), temps, flags

    def _ingest(self, decoder, cache, sid, tokens, k: int, n: int,
                sampling):
        """ONE bucketed prompt-ingest dispatch — tokens[k:k+n] into
        ``sid``'s pages, via fresh prefill at k == 0 or the traced
        context-prefill continuation otherwise.  THE single dispatch
        choice both the serving prefill path (:meth:`_prefill_chunk`)
        and the replay primitive (:meth:`_replay_kv`) ride, so the
        replay's bit-exactness contract can never drift from the path
        it replays."""
        ids = tokens[None, k:k + n]
        if k:
            return decoder.chunk_prefill(cache, [sid], ids,
                                         context_tokens=k, bucket=True,
                                         sampling=sampling)
        return decoder.prefill(cache, [sid], ids, bucket=True,
                               sampling=sampling)

    def _prefill_chunk(self, req, n: int) -> bool:
        """Ingest the next ``n`` tokens of ``req``'s prefill target in
        ONE compiled dispatch (bucketed: one compile per power-of-two
        chunk length, not one per distinct length).  The target is the
        prompt — or, for a restored request, prompt + generated-so-far:
        the replay primitive's admission-path mode (ISSUE 8) rides the
        SAME program.  Returns True when the target is fully resident —
        only then is the next token sampled (with the SAME (seed,
        absolute position) counter as a monolithic prefill, so chunked,
        preempted and replayed prefill are greedy- and sample-replay-
        identical to the unchunked path).

        Intermediate chunks run the fused-sampling program in its
        argmax-only tail — the per-chunk host transfer stays (1,) ids
        whose value is discarded."""
        target = req.prefill_target
        k = req.prefill_pos
        total = len(target)
        n = min(n, total - k)
        last = (k + n == total)
        if not self.sample_on_device:
            sampling = None
        elif last:
            sampling = self._sampling_for([req], [total])
        else:
            sampling = _null_sampling()
        self._wedged.clear()      # only THIS dispatch may flag itself
        t0 = self._step_started_at = time.monotonic()
        t_tr = _tracer.now_ns() if _tracer.enabled else 0
        try:
            if req.chunks_done == 0:
                # per-sequence site, once — chunking must not change
                # existing fault plans' semantics
                _faults.maybe_fire("prefill", seq_ids=[req.seq_id])
            _faults.maybe_fire("prefill_chunk", seq_ids=[req.seq_id])
            self._count_dispatch("chunk" if k else "prefill")
            with monitor.span("engine/prefill", histogram=_prefill_s):
                out = self._ingest(self._decoder, self.cache, req.seq_id,
                                   target, k, n, sampling)
        finally:
            self._step_started_at = None
        _last_step_ts.set(time.time())
        try:
            self._check_wedged(t0)      # same stale-fire guard as decode
        except _EngineWedged:
            # the watchdog flagged this dispatch as wedged: its writes
            # are suspect — roll the cache back to the chunk's start
            # so the caller's rebuild + replay + retry is exact
            self.cache.truncate(req.seq_id, k)
            raise
        req.prefill_pos = k + n
        req.chunks_done += 1
        self._sched.note_chunk(req)
        if _tracer.enabled and t_tr:
            # one step-track entry per chunk dispatch + the request's
            # own timeline entry — flow-linked in the chrome export
            # (t_tr == 0 means the window opened MID-dispatch: skip the
            # slice rather than emit one starting at clock zero)
            _tracer.step_record(
                "prefill_chunk", self.steps, t_tr, _tracer.now_ns(),
                request=req.request_id, tokens=n, pos=k,
                cls=req.priority)
            _tracer.request_event(req.request_id, "prefill_chunk",
                                  tokens=n, pos=k,
                                  chunk=req.chunks_done)
        if not last:
            return False
        self._finish_prefill(req, out[0], sampling is not None)
        return True

    def _finish_prefill(self, req, out_row, sampled: bool) -> None:
        """Prefill-completion side effects, shared by the legacy chunk
        path and the unified ragged step: the target is fully resident
        — register its prefix, ingest the draft's copy, latch the first
        sampled token, stamp TTFT, journal the pending sample."""
        # ---- target fully resident: finish what monolithic prefill did
        if self.prefix_cache:
            _prefix_lookups.inc()
            if req.prefix_tokens:
                _prefix_hits.inc()
                _prefix_hit_tokens.inc(req.prefix_tokens)
            # retain this prompt's page-aligned prefixes for later
            # sharers (idempotent for the pages it itself shared);
            # chunk-written pages carry identical KV, so chunked
            # prompts seed the prefix cache exactly like monolithic ones
            self.cache.register_prefix(req.seq_id, req.prompt)
            self._journal_pages(req, "registered", len(req.prompt))
        if req.use_draft:
            # the draft ingests the WHOLE target (no prefix sharing in
            # its pool) so its cache sits at the same length as the
            # target's — the lockstep invariant every propose/verify
            # round preserves.  Deferred to prefill COMPLETION under
            # chunking: a preempted target resumes without ever having
            # touched the draft pool.  The greedy-tail sampling keeps
            # the transfer at (1,) ids; the value is discarded.
            try:
                self._count_dispatch("draft")
                self._draft_decoder.prefill(
                    self.draft_cache, [req.seq_id],
                    req.prefill_target[None],
                    bucket=True, sampling=_null_sampling())
            except BaseException:  # noqa: BLE001 — degrade, don't fail
                self._downgrade_draft([req])
        if req.next_token is None:
            # a restored request keeps its journaled next token (the
            # replayed final draw equals it by the counter contract);
            # sampled rows on the host-logits path must ALSO keep it —
            # re-picking would burn a host RNG draw
            req.next_token = (int(out_row) if sampled
                              else self._pick(req, out_row))
        req.first_token_at = time.perf_counter()
        ttft = req.first_token_at - req.submitted_at
        _ttft_s.observe(ttft)
        self._sched.note_first_token(req, ttft)
        _tracer.request_event(req.request_id, "first_token",
                              ttft_s=round(ttft, 6))
        if self.journal is not None:
            # prefill completion: no tokens appended yet, but the first
            # pending sample is host state a SIGKILL must not lose
            self._jrows.append((req.request_id, (), req.next_token))

    def _run_chunks(self, plan) -> None:
        """Execute one iteration's prefill chunk plan (device work —
        called WITHOUT the lock).  A failing chunk quarantines exactly
        its request: the decoder already rolled the failed dispatch
        back, retirement reclaims the pages every EARLIER chunk wrote,
        and batchmates/other tenants are untouched (host-side faults
        leave the donated pools valid — see _recover_pools)."""
        completed: List[_Request] = []
        failed: List[_Request] = []
        for req, n in plan:
            if req.cancelled or req.done.is_set():
                # cancelled: the next reap retires it; done: a replay
                # failure during an earlier chunk's recovery already
                # quarantined it
                continue
            try:
                if self._prefill_chunk(req, n):
                    completed.append(req)
            except _EngineWedged as e:
                # watchdog-flagged wedge mid-prefill: bounded rebuild
                # (pools reset, every survivor's KV replayed — this
                # request's earlier chunks included) then ONE retry of
                # the same chunk; a second failure quarantines as usual
                self._after_step_failure(e)
                if req.done.is_set():
                    # its OWN replay failed during the rebuild: already
                    # quarantined and retired — retrying would write
                    # into pages nothing will ever free
                    continue
                try:
                    if self._prefill_chunk(req, n):
                        completed.append(req)
                except BaseException as e2:  # noqa: BLE001
                    req.error = e2
                    failed.append(req)
                    self._after_step_failure(e2, exclude=(req,))
            except BaseException as e:  # noqa: BLE001 — quarantine one
                req.error = e
                failed.append(req)
                # a REAL donated-buffer loss in this chunk zeroed every
                # OTHER sequence's KV too: detect the pool rebuild and
                # replay the survivors before the next dispatch runs
                # over zeroed pools (no-op for host-side faults)
                self._after_step_failure(e, exclude=(req,))
        if not completed and not failed:
            return
        with self._cond:
            for r in failed:
                if r in self._prefilling:
                    self._prefilling.remove(r)
                # quarantine BEFORE retire so the timeline's terminal
                # event matches the decode-path ejection sites
                # (consumers classify an ended request by last event)
                _note_quarantine(r)
                self._retire_locked(r)
            for r in completed:
                if r in self._prefilling:
                    self._prefilling.remove(r)
                    self._active.append(r)
            self._cond.notify_all()
        for r in failed:
            r.done.set()

    # ------------------------------------------- unified ragged step
    def _legacy_iteration(self) -> bool:
        """True when THIS iteration must run the legacy multi-dispatch
        composition: the ``unified_step=False`` escape hatch, the
        repeated-failure latch, or an installed fault plan targeting
        the legacy dispatch sites (chaos plans' quarantine semantics
        are defined against per-mode dispatch granularity — one
        poisoned chunk fails one request — which a single fused
        dispatch would widen).  Delay-kind rules on the dispatch
        sites themselves (prefill/prefill_chunk/decode_step) are
        pacing, not failure injection: the unified step fires those
        sites itself, so they do NOT divert."""
        if not self.unified_step or self._unified_off:
            return True
        plan = _faults.active()
        return plan is not None and any(
            r.site in _ENGINE_FAULT_SITES
            and not (r.kind == "delay"
                     and r.site in _PACING_FAULT_SITES)
            for r in plan.rules)

    def _disable_unified_locked(self) -> None:
        """Caller holds ``self._cond``.  Latch the unified path off
        after repeated ragged-dispatch failures: the legacy
        composition — whose retry/bisect isolation just absorbed those
        failures row by row — serves from here on."""
        self._unified_off = True

    def _propose_drafts(self, reqs):
        """Draft-model propose for the unified step — the legacy
        ``_exec_spec_step`` propose block: ONE compiled scan dispatch
        for the opted-in rows.  A draft failure downgrades them to
        plain decode (their drafts stay ``-1``, which never matches:
        they ride the verify rows with unmatched slots and advance
        exactly one token, exactly as the legacy path degrades)."""
        k = self.spec_k
        drafts = np.full((len(reqs), k), -1, np.int32)
        d_idx = [i for i, r in enumerate(reqs) if r.use_draft]
        if not d_idx:
            return drafts
        Bd = self._bucket(len(d_idx))
        d_seqs = [reqs[i].seq_id for i in d_idx]
        d_tok = np.array([reqs[i].generated[-1] for i in d_idx],
                         np.int32)
        d_pos = np.array([self.draft_cache.length(s) for s in d_seqs],
                         np.int32)
        if Bd > len(d_idx):
            self.draft_cache.truncate(_PAD_SEQ, 0)
            pad_n = Bd - len(d_idx)
            d_seqs += [_PAD_SEQ] * pad_n
            d_tok = np.concatenate([d_tok, np.zeros(pad_n, np.int32)])
            d_pos = np.concatenate([d_pos, np.zeros(pad_n, np.int32)])
        try:
            self._count_dispatch("draft")
            prop = self._draft_decoder.multi_step(
                self.draft_cache, d_seqs, d_tok, d_pos, k + 1)
        except BaseException:  # noqa: BLE001 — degrade, don't fail
            self._downgrade_draft([reqs[i] for i in d_idx])
        else:
            for j, i in enumerate(d_idx):
                drafts[i] = prop[j, :k]
        return drafts

    def _unified_rollback(self, chunks, active, lens_before) -> None:
        """Undo the unified composition after a failed (or wedged)
        ragged dispatch, so the legacy re-run replays the EXACT same
        step: appended decode tokens pop, every row's cache length
        returns to its pre-step value (the decoder rolled its own
        advance back on a host/device error; a wedge's advance stands
        until this truncate), and speculative rows unwind the draft
        cache the propose scan advanced."""
        for req, _target, k, _n, _last in chunks:
            self.cache.truncate(req.seq_id, k)
        for r in active:
            r.generated.pop()
            tgt, dft = lens_before[r.seq_id]
            self.cache.truncate(r.seq_id, tgt)
            if dft is not None and self._spec:
                self.draft_cache.truncate(r.seq_id, dft)

    def _unified_step(self, plan) -> None:
        """ONE ragged dispatch for the whole iteration (ISSUE 17): the
        scheduler's rank-ordered chunk plan feeds prefill/chunk row
        spans directly, every active row contributes its decode token
        — or, under speculation, a (k+1)-token verify row of freshly
        proposed drafts — and the single compiled ``ragged_step`` call
        replaces the legacy decode-vs-chunk dispatch alternation.
        Post-processing replays the legacy paths' side effects
        exactly: chunk bookkeeping and prefill completion
        (:meth:`_finish_prefill`), retirement/journal/steps accounting
        from ``_decode_step``, speculative accept consumption with
        partial rollback from ``_exec_spec_step``.

        On ANY failure the composition unwinds
        (:meth:`_unified_rollback`), pools rebuild + survivors replay
        if a device-side loss zeroed them, and the iteration re-runs
        through the legacy composition — whose retry/bisect machinery
        owns failure isolation; repeated failures latch the unified
        path off entirely."""
        chunks = []
        for req, n in plan:
            if req.cancelled or req.done.is_set():
                continue
            target = req.prefill_target
            k = req.prefill_pos
            n = min(n, len(target) - k)
            chunks.append((req, target, k, n, k + n == len(target)))
        active = list(self._active)
        if not chunks and not active:
            return
        spec = self._spec and any(r.use_draft for r in active)
        k_spec = self.spec_k if spec else 0
        lens_before = {
            r.seq_id: (self.cache.length(r.seq_id),
                       (self.draft_cache.length(r.seq_id)
                        if self._spec and r.use_draft else None))
            for r in active}
        jlens = ({id(r): len(r.generated) for r in active}
                 if self.journal is not None else None)
        for r in active:
            r.generated.append(r.next_token)
        if active:
            _active_seqs.set(len(active))
            _batch_occupancy.observe(len(active) / self.max_batch)
            _sampling_on_device_g.set(int(self.sample_on_device))
        drafts = None
        t_tr = _tracer.now_ns() if _tracer.enabled else 0
        try:
            if spec:
                drafts = self._propose_drafts(active)
            nchunks = len(chunks)
            seq_ids, rows, ctxs, nds = [], [], [], []
            for req, target, k, n, _last in chunks:
                seq_ids.append(req.seq_id)
                rows.append(np.asarray(target[k:k + n], np.int32))
                ctxs.append(k)
                nds.append(0)
            for i, r in enumerate(active):
                seq_ids.append(r.seq_id)
                if spec:
                    row = np.empty(k_spec + 1, np.int32)
                    row[0] = r.generated[-1]
                    row[1:] = drafts[i]
                    nds.append(k_spec)
                else:
                    row = np.asarray([r.generated[-1]], np.int32)
                    nds.append(0)
                rows.append(row)
                ctxs.append(self.cache.length(r.seq_id))
            if self.sample_on_device:
                b = len(seq_ids)
                seeds = np.zeros(b, np.uint32)
                temps = np.ones(b, np.float32)
                flags = np.zeros(b, bool)
                # the draw counter is computed IN-PROGRAM per row
                # (ctx + span - drafts + accept), so chunk-final,
                # decode and verify draws all land on the row's
                # absolute token position — the replay-stable counter
                # contract.  Intermediate chunk rows draw nothing.
                live = [req if last else None
                        for req, _t, _k, _n, last in chunks] + active
                for i, r in enumerate(live):
                    if r is None:
                        continue
                    seeds[i] = r.seed
                    temps[i] = max(r.temperature, 1e-6)
                    flags[i] = r.do_sample
                sampling = (seeds, temps, flags)
            else:
                sampling = None
            self._wedged.clear()
            t0 = self._step_started_at = time.monotonic()
            try:
                # only delay-kind pacing rules can be live here
                # (_legacy_iteration diverts everything else): fire
                # the legacy sites so throttling plans — per-row
                # seq_id targeting included — pace the unified step
                # exactly as they pace the composition it replaces
                for req, _t, k, _n, _l in chunks:
                    if not k:
                        _faults.maybe_fire("prefill",
                                           seq_ids=[req.seq_id])
                    _faults.maybe_fire("prefill_chunk",
                                       seq_ids=[req.seq_id])
                if active:
                    _faults.maybe_fire(
                        "decode_step",
                        seq_ids=[r.seq_id for r in active])
                hist = _decode_step_s if active else _prefill_s
                with monitor.span("engine/ragged_step", histogram=hist):
                    self._count_dispatch("ragged")
                    out, accept = self._decoder.ragged_step(
                        self.cache, seq_ids, rows, ctxs,
                        n_drafts=(nds if spec else None),
                        sampling=sampling)
                    self._check_wedged(t0)
            finally:
                self._step_started_at = None
            _last_step_ts.set(time.time())
        except BaseException as e:  # noqa: BLE001 — legacy owns isolation
            self._unified_rollback(chunks, active, lens_before)
            _unified_fallbacks.inc()
            self._unified_failures += 1
            if self._unified_failures >= 3 and not self._unified_off:
                with self._cond:
                    self._disable_unified_locked()
            # a device-side loss zeroed every survivor's KV: rebuild +
            # replay BEFORE the legacy re-run decodes over zeroed pages
            # (replay-dead requests are quarantined/ejected in here)
            self._after_step_failure(e)
            self._run_chunks(plan)
            if self._active:
                self._decode_step()
            return
        self._unified_failures = 0
        now_ns = _tracer.now_ns() if _tracer.enabled and t_tr else 0
        # ---- chunk rows: the legacy _prefill_chunk bookkeeping
        completed: List[_Request] = []
        for i, (req, _target, k, n, last) in enumerate(chunks):
            req.prefill_pos = k + n
            req.chunks_done += 1
            self._sched.note_chunk(req)
            if _tracer.enabled and t_tr:
                _tracer.step_record(
                    "prefill_chunk", self.steps, t_tr, now_ns,
                    request=req.request_id, tokens=n, pos=k,
                    cls=req.priority)
                _tracer.request_event(req.request_id, "prefill_chunk",
                                      tokens=n, pos=k,
                                      chunk=req.chunks_done)
            if last:
                completed.append(req)
                self._finish_prefill(req, out[i], sampling is not None)
        # ---- decode/verify rows: the legacy _decode_step retirement
        still, retired = [], []
        accepted_emitted = 0
        if active:
            srows = []
            d_idx = ([i for i, r in enumerate(active) if r.use_draft]
                     if spec else [])
            for i, r in enumerate(active):
                if spec:
                    a = int(accept[nchunks + i])
                    # page-granular partial rollback, both caches —
                    # the _exec_spec_step contract
                    new_len = lens_before[r.seq_id][0] + a + 1
                    self.cache.truncate(r.seq_id, new_len)
                    if r.use_draft:
                        self.draft_cache.truncate(r.seq_id, new_len)
                    srows.append(_SpecRow(out[nchunks + i], a,
                                          drafts[i]))
                else:
                    srows.append(out[nchunks + i])
            if spec:
                self._last_spec = (
                    k_spec * len(d_idx),
                    sum(int(accept[nchunks + i]) for i in d_idx))
                if d_idx:
                    _spec_proposed.inc(k_spec * len(d_idx))
                    _spec_accepted.inc(self._last_spec[1])
                    rejected = 0
                    for i in d_idx:
                        _spec_accept_len.observe(
                            int(accept[nchunks + i]))
                        rejected += int(accept[nchunks + i]) < k_spec
                    if rejected:
                        _spec_rollback.inc(rejected)
                _spec_draft_pages.set(self.draft_cache.pinned_pages)
            else:
                self._last_spec = (0, 0)
            if _tracer.enabled and t_tr:
                comp: dict = {}
                for r in active:
                    comp[r.priority] = comp.get(r.priority, 0) + 1
                prop, acc = self._last_spec
                _tracer.step_record(
                    "decode", self.steps, t_tr, now_ns,
                    batch=len(active), classes=comp,
                    spec_proposed=prop, spec_accepted=acc, poisoned=0,
                    requests=[r.request_id for r in active])
            _tokens_total.inc(len(active))
            on_device = self.sample_on_device
            for r, row in zip(active, srows):
                if _tracer.enabled:
                    if isinstance(row, _SpecRow):
                        _tracer.request_event(
                            r.request_id, "verify_step",
                            step=self.steps, accept=int(row.accept))
                    else:
                        _tracer.request_event(r.request_id,
                                              "decode_step",
                                              step=self.steps)
                eos_hit = (r.eos_token_id is not None
                           and r.generated[-1] == r.eos_token_id)
                if eos_hit or len(r.generated) >= r.max_new_tokens:
                    retired.append(r)
                    continue
                if isinstance(row, _SpecRow):
                    done = False
                    for t in row.drafts[:row.accept]:
                        r.generated.append(int(t))
                        accepted_emitted += 1
                        if (r.eos_token_id is not None
                                and int(t) == r.eos_token_id) \
                                or len(r.generated) >= r.max_new_tokens:
                            done = True
                            break
                    if done:
                        retired.append(r)
                        continue
                    out_row = row.out
                else:
                    out_row = row
                r.next_token = (int(out_row) if on_device
                                else self._pick(r, out_row))
                still.append(r)
            if accepted_emitted:
                _tokens_total.inc(accepted_emitted)
            if self.journal is not None:
                for r in still:
                    self._jrows.append(
                        (r.request_id,
                         list(r.generated[jlens[id(r)]:]),
                         r.next_token))
        with self._cond:
            if active:
                self.steps += 1
                for r in retired:
                    self._retire_locked(r)
                self._active = still
                if not still:
                    self._free_pads_locked()
            for r in completed:
                if r in self._prefilling:
                    self._prefilling.remove(r)
                    self._active.append(r)
            self._cond.notify_all()
        if active:
            _active_seqs.set(len(still))
        for r in retired:
            r.done.set()

    def _pick(self, req, logits_row) -> int:
        from .paged import sample_token
        return sample_token(logits_row, req.do_sample, req.temperature,
                            req.rng)

    def _release_draft_locked(self, req) -> None:
        """Caller holds ``self._cond``.  Free the request's draft-cache
        pages and return exactly the reservation they covered (the
        draft pool has no prefix index, so every freed page is truly
        free).  Idempotent via the per-request flag — downgrade and
        retirement may both reach here."""
        if not req._draft_reserved:
            return
        slack = (self._pages_for(req)
                 - len(self.draft_cache._seq_pages.get(req.seq_id, ())))
        released = self.draft_cache.free(req.seq_id)
        self._reserved_draft_pages -= slack + released
        req._draft_reserved = False

    def _downgrade_draft(self, reqs) -> None:
        """Speculation is an optimization: after a draft-side failure
        the affected requests keep decoding on the plain path instead
        of being quarantined.  Sticky for the request's lifetime (a
        desynced draft cache cannot rejoin lockstep mid-stream)."""
        _spec_draft_failures.inc(len(list(reqs)))
        with self._cond:
            for r in reqs:
                r.use_draft = False
                self._release_draft_locked(r)

    def _retire_locked(self, req):
        """Caller holds ``self._cond``.  Release the request's pages and
        exactly the reservation its retirement uncovers: the worst-case
        pages it never allocated, plus each held page that stopped being
        pinned (a shared page another live sharer still maps keeps its
        reservation — it transfers to that sharer's accounting)."""
        slack = (self._pages_for(req)
                 - len(self.cache._seq_pages.get(req.seq_id, ())))
        released = self.cache.free(req.seq_id)
        self._reserved_pages -= slack + released
        self._release_draft_locked(req)
        req.finished_at = time.perf_counter()
        if req.error is None:
            _gen_latency_s.observe(req.finished_at - req.submitted_at)
        self._sched.note_retired(req)   # per-class TPOT (no-op on error)
        self._cache_result_locked(req)
        self._journal_retire(req)
        _tracer.request_event(
            req.request_id, "retire", ok=req.error is None,
            generated=len(req.generated),
            latency_s=round(req.finished_at - req.submitted_at, 6))

    def _bucket(self, n: int) -> int:
        from .paged import next_pow2
        return min(next_pow2(n), self.max_batch)

    # ------------------------------------------- crash recovery (ISSUE 8)
    def _pools_rebuilt(self) -> bool:
        """True exactly once per pool-rebuild event: compares the
        caches' ``generation`` counters (bumped by ``reset_pools``
        after a consumed donated buffer) against the last value the
        engine reconciled.  Scheduler-thread only."""
        g = self.cache.generation + (
            self.draft_cache.generation if self._spec else 0)
        if g == self._pool_gen:
            return False
        self._pool_gen = g
        return True

    def _replay_kv(self, req, upto=None, dlen=None) -> None:
        """THE replay primitive (ISSUE 8 tentpole): reconstruct one
        sequence's KV state by re-prefilling its token sequence —
        ``prompt + generated-so-far``, up to the CURRENT logical cache
        length — through the existing (chunked) context-prefill
        program, into the pages the sequence already maps (same
        (page, slot) plan, so shared prefix pages are rewritten with
        identical content whichever sharer replays first).

        Bit-exact by construction: prompt/generated are host state, the
        weights are unchanged, and the fused sampler draws by (seed,
        absolute position) — so the KV a replayed chunk writes is the
        KV the original prefill/decode wrote.  The pending
        ``next_token`` is host state too and is NOT resampled; replay
        outputs are discarded (argmax-only tail).  The draft cache is
        re-prefilled to its own length so the lockstep invariant
        survives the rebuild.

        ``upto``/``dlen`` override the replay targets — the batched
        path records them before truncating anything, so its per-row
        fallback can still replay a row a failed batched attempt left
        at a partial length."""
        sid = req.seq_id
        if upto is None:
            upto = self.cache.length(sid)
        if dlen is None:
            dlen = (self.draft_cache.length(sid)
                    if self._spec and req.use_draft else 0)
        if upto <= 0 and dlen <= 0:
            return                     # nothing resident yet
        sampling = _null_sampling() if self.sample_on_device else None
        if upto > 0:
            tokens = req.output_ids[:upto]
            self.cache.truncate(sid, 0)
            chunk = self.prefill_chunk_tokens or upto
            k = 0
            while k < upto:
                n = min(chunk, upto - k)
                # the heartbeat must age during replay dispatches too:
                # a recovery that wedges on the still-sick device has
                # to be as visible to the watchdog as the step that
                # triggered it (the stale flag is cleared at the next
                # step's start, so a slow replay never condemns it)
                self._step_started_at = time.monotonic()
                try:
                    _replay_dispatches.inc()
                    self._ingest(self._decoder, self.cache, sid, tokens,
                                 k, n, sampling)
                finally:
                    self._step_started_at = None
                k += n
            if self.prefix_cache and upto >= len(req.prompt):
                # re-seed the prefix index the pool rebuild dropped:
                # the entry's page refcounts come back with it
                self.cache.register_prefix(sid, req.prompt)
        if dlen > 0:
            # the draft pool rides in lockstep — rebuild its KV to its
            # own pre-loss length from the same host-side tokens
            self.draft_cache.truncate(sid, 0)
            self._step_started_at = time.monotonic()
            try:
                _replay_dispatches.inc()
                self._draft_decoder.prefill(
                    self.draft_cache, [sid], req.output_ids[None, :dlen],
                    bucket=True, sampling=sampling)
            finally:
                self._step_started_at = None
        _survivor_replays.inc()
        _tracer.request_event(req.request_id, "replay",
                              tokens=int(upto), draft_tokens=int(dlen))

    def _replay_kv_batch(self, rows, targets) -> None:
        """Batched survivor replay (ISSUE 9 satellite, ROADMAP crash-
        consistency follow-up (c)): reconstruct MANY survivors' KV in
        lockstep chunk rounds — each round ingests up to a chunk budget
        per row for up to ``max_batch`` rows in ONE compiled dispatch
        through the decoder's batched context-prefill program (per-row
        context lengths are traced, so mixed-progress rows share the
        dispatch).  For continuation chunks (k > 0) this is the SAME
        traced "prefix" program the per-row path compiles — only the
        dispatch count changes, which is the MTTR lever on
        many-survivor pools.  Caveat carried with the TPU capture
        window: a row's FIRST chunk originally ingested through the
        "prefill" program (flash attention), while the batched k == 0
        round runs the prefix program's dense masked attention — on
        CPU both lower to identical XLA math (tier-1 locks the
        bit-exactness), on real TPU the two kernels' accumulation
        orders may differ in ulps, so hardware replay exactness must
        be re-verified there (``replay_batch=False`` restores the
        per-row path, whose k == 0 chunk uses the original prefill
        program).

        ``targets`` maps ``id(req)`` to the (upto, dlen) lengths
        recorded BEFORE any truncation; any failure propagates to the
        caller, which falls back to per-row replay for exact
        quarantine isolation."""
        def collect(cache, which):
            out = []
            for r in rows:
                upto = targets[id(r)][which]
                if upto > 0:
                    out.append((r, r.output_ids[:upto], upto))
                    cache.truncate(r.seq_id, 0)
            return out

        def rounds(decoder, cache, work, chunk):
            """ONE lockstep-round loop for both pools: up to max_batch
            rows per batched dispatch, each ingesting up to a chunk
            budget, dropping out as it reaches its target length."""
            cursor = {id(r): 0 for r, _, _ in work}
            pending = list(work)
            while pending:
                batch = pending[:self.max_batch]
                sids = [r.seq_id for r, _, _ in batch]
                ks = [cursor[id(r)] for r, _, _ in batch]
                slices = [toks[k:k + min(chunk or upto, upto - k)]
                          for (r, toks, upto), k in zip(batch, ks)]
                self._step_started_at = time.monotonic()
                try:
                    _replay_dispatches.inc()
                    decoder.batch_context_prefill(
                        cache, sids, slices, ks,
                        sampling=(_null_sampling(len(sids))
                                  if self.sample_on_device else None))
                finally:
                    self._step_started_at = None
                for (r, toks, upto), sl in zip(batch, slices):
                    cursor[id(r)] += len(sl)
                pending = [(r, toks, upto) for r, toks, upto in pending
                           if cursor[id(r)] < upto]

        chunk = self.prefill_chunk_tokens
        work = collect(self.cache, 0)
        rounds(self._decoder, self.cache, work, chunk)
        for r, toks, upto in work:
            if self.prefix_cache and upto >= len(r.prompt):
                self.cache.register_prefix(r.seq_id, r.prompt)
        # draft pools ride in lockstep: batched rounds over the draft
        # decoder's batched program (context starts at 0 — the draft
        # always holds whole prompts)
        dwork = collect(self.draft_cache, 1) if self._spec else []
        if dwork:
            rounds(self._draft_decoder, self.draft_cache, dwork, chunk)
        done = {id(r) for r, _, _ in work} | {id(r) for r, _, _ in dwork}
        _survivor_replays.inc(len(done))
        if _tracer.enabled:
            seen = set()
            for r, _, _ in work + dwork:
                if id(r) in seen:
                    continue
                seen.add(id(r))
                _tracer.request_event(
                    r.request_id, "replay", batched=True,
                    tokens=int(targets[id(r)][0]),
                    draft_tokens=int(targets[id(r)][1]))

    def _replay_survivors(self, exclude=()) -> List[_Request]:
        """Device-failure recovery (ISSUE 8 consumer 1): replay every
        live sequence — active, mid-prefill and preempted — to its
        current logical length after a pool rebuild zeroed the device
        KV.  ``exclude`` names requests about to be quarantined (their
        replay would be wasted work).  Scheduler-thread only: the three
        lists are stable while the loop thread is here.

        A replay that ITSELF fails (the device fault is pinned to that
        sequence) marks the request with the error and returns it for
        quarantine — one unreconstructible row must never fail the
        engine; if the failed replay consumed the pools again, the
        whole pass restarts so earlier survivors are re-replayed over
        the fresh pools (bounded: every restart removes a row).

        With ``replay_batch`` (the default everywhere but TPU, where
        the batched round's kernel swap is not yet hardware-verified
        bit-exact) survivors replay in
        BATCHED lockstep rounds — many rows per compiled dispatch
        (ISSUE 9 satellite; the MTTR lever).  A failed batched dispatch
        cannot name the poisoned row, so it falls back to the per-row
        pass, which preserves exact quarantine isolation."""
        skip = {id(r) for r in exclude}
        failed: List[_Request] = []

        def eligible():
            return [r for r in (self._active + self._prefilling
                                + self._preempted)
                    # r.error covers rows an EARLIER recovery in this
                    # same step already condemned (their done event is
                    # only set at step end) — never re-replay one
                    if id(r) not in skip and r.seq_id is not None
                    and not r.done.is_set() and r.error is None]

        # replay targets recorded BEFORE any truncation: the batched
        # path's per-row fallback must know the full lengths even after
        # a mid-round failure left a row partially re-ingested
        targets = {id(r): (self.cache.length(r.seq_id),
                           (self.draft_cache.length(r.seq_id)
                            if self._spec and r.use_draft else 0))
                   for r in eligible()}
        batched = self.replay_batch
        while True:
            restart = False
            rows = eligible()
            if batched and len(rows) > 1:
                try:
                    self._replay_kv_batch(rows, targets)
                    break
                except BaseException:  # noqa: BLE001 — isolate per row
                    batched = False
                    self._pools_rebuilt()   # reconcile a mid-batch loss
                    continue
            for r in rows:
                try:
                    self._replay_kv(r, *targets[id(r)])
                except BaseException as e:  # noqa: BLE001 — per-row
                    r.error = e
                    skip.add(id(r))
                    failed.append(r)
                    if self._pools_rebuilt():
                        restart = True
                        break
            if not restart:
                break
        return failed

    def _after_step_failure(self, error=None, exclude=(),
                            in_step: bool = False) -> List[_Request]:
        """Recovery hook run after ANY failed (or wedged) step/chunk
        was rolled back: a wedge rebuilds the pools outright
        (consumer 2 — the watchdog-driven restart); then, if the pools
        were rebuilt by anyone (here, or the decoder after a REAL
        donated-buffer loss), every survivor's KV is replayed before
        the caller retries — so a retry/bisect never decodes over
        zeroed pages and quarantine stays per-request for device-side
        failures too.

        Requests whose own replay failed are quarantined: with
        ``in_step`` the ones in the active batch are RETURNED (the
        step caller must drop them from its retry and treat them as
        poisoned — they carry an un-executed token to pop); everything
        else is retired here."""
        if isinstance(error, _EngineWedged):
            self.cache.reset_pools()
            if self._spec:
                self.draft_cache.reset_pools()
        if not self._pools_rebuilt():
            return []
        _rebuilds_total.inc()
        t_tr = _tracer.now_ns() if _tracer.enabled else 0
        with monitor.span("engine/recovery", histogram=_recovery_s):
            failed = self._replay_survivors(exclude=exclude)
        if _tracer.enabled and t_tr:
            _tracer.step_record(
                "recovery", self.steps, t_tr, _tracer.now_ns(),
                wedged=isinstance(error, _EngineWedged),
                replay_failed=len(failed))
        if not failed:
            return []
        caller_owned = ([r for r in failed if r in self._active]
                        if in_step else [])
        eject = [r for r in failed if r not in caller_owned]
        if eject:
            with self._cond:
                for r in eject:
                    for lst_name in ("_active", "_prefilling",
                                     "_preempted"):
                        lst = getattr(self, lst_name)
                        if r in lst:
                            lst.remove(r)
                    # quarantine BEFORE retire: terminal timeline event
                    # stays 'retire' at every ejection site
                    _note_quarantine(r)
                    self._retire_locked(r)
                self._cond.notify_all()
            for r in eject:
                r.done.set()
        return caller_owned

    def _check_wedged(self, started_at: Optional[float] = None) -> None:
        """Consume the watchdog's wedge flag: raised as a step failure
        so the retry/bisect ladder (plus ``_after_step_failure``'s
        rebuild) handles it like any other suspect step.

        ``started_at`` guards against a STALE fire: the watchdog reads
        the heartbeat age and invokes ``on_timeout`` as two separate
        actions, so a fire aimed at a slow dispatch (e.g. a recovery
        replay compiling a program) can be delivered AFTER the next
        dispatch already cleared the flag — and without this guard
        that fresh dispatch would be condemned, quarantining a healthy
        single-row batch on its second "failure".  A dispatch that ran
        for less than ``step_timeout_s`` provably did not wedge."""
        if not self._wedged.is_set():
            return
        self._wedged.clear()
        if started_at is not None and self.step_timeout_s is not None \
                and time.monotonic() - started_at \
                <= float(self.step_timeout_s):
            return                   # stale fire: not this dispatch
        raise _EngineWedged(
            "decode step exceeded the watchdog heartbeat timeout; "
            "treating its results as suspect")

    # ------------------------------------------------- decode + isolation
    def _spec_sampling_for(self, reqs, n: int):
        """(seeds, temps, flags) arrays for the verify program's fused
        bonus-token tail, padded to ``n`` rows — ``_sampling_for``
        minus the host-side counters: the draw position is
        pos + accept + 1, computed on device, so plain and speculative
        draws replay identically by construction."""
        seeds, _, temps, flags = self._sampling_for(
            reqs, np.zeros(n, np.int32))
        return seeds, temps, flags

    def _exec_spec_step(self, reqs) -> List[_SpecRow]:
        """One SPECULATIVE decode step for ``reqs``: the draft proposes
        ``spec_k`` greedy tokens per opted-in row in ONE compiled scan
        dispatch (plus one write-only step so its cache covers the last
        proposal), then the target verifies the whole ``[B, k+1]``
        block in ONE compiled dispatch — per-row accept lengths and the
        bonus token computed on device.  Rows that opted out (or whose
        draft just failed) ride along with unmatched draft slots: they
        advance exactly one token, exactly as a plain step would.

        Replays identically after a rollback (greedy draft + the same
        threefry counters), which the retry/bisect recovery depends on.
        Partial rollback happens HERE: both caches truncate to each
        row's verified length pos + accept + 1 before returning."""
        k = self.spec_k
        B = self._bucket(len(reqs))
        npad = B - len(reqs)
        drafts = np.full((len(reqs), k), -1, np.int32)  # -1 never matches
        d_idx = [i for i, r in enumerate(reqs) if r.use_draft]
        # a flag raised against an EARLIER dispatch (one that errored
        # before its own _check_wedged, or a slow replay) must not
        # condemn this fresh step to a needless rebuild
        self._wedged.clear()
        t0 = self._step_started_at = time.monotonic()
        try:
            _faults.maybe_fire("decode_step",
                               seq_ids=[r.seq_id for r in reqs])
            _faults.maybe_fire("engine_wedge",
                               seq_ids=[r.seq_id for r in reqs])
            with monitor.span("engine/decode_step",
                              histogram=_decode_step_s):
                if d_idx:
                    Bd = self._bucket(len(d_idx))
                    d_seqs = [reqs[i].seq_id for i in d_idx]
                    d_tok = np.array(
                        [reqs[i].generated[-1] for i in d_idx], np.int32)
                    d_pos = np.array(
                        [self.draft_cache.length(s) for s in d_seqs],
                        np.int32)
                    if Bd > len(d_idx):
                        self.draft_cache.truncate(_PAD_SEQ, 0)
                        pad_n = Bd - len(d_idx)
                        d_seqs += [_PAD_SEQ] * pad_n
                        d_tok = np.concatenate(
                            [d_tok, np.zeros(pad_n, np.int32)])
                        d_pos = np.concatenate(
                            [d_pos, np.zeros(pad_n, np.int32)])
                    try:
                        self._count_dispatch("draft")
                        prop = self._draft_decoder.multi_step(
                            self.draft_cache, d_seqs, d_tok, d_pos, k + 1)
                    except BaseException:  # noqa: BLE001 — degrade
                        # a draft failure must never fail the batch:
                        # those rows decode plain from here on (their
                        # draft cache cannot rejoin lockstep)
                        self._downgrade_draft([reqs[i] for i in d_idx])
                        d_idx = []
                    else:
                        for j, i in enumerate(d_idx):
                            drafts[i] = prop[j, :k]
                block = np.zeros((B, k + 1), np.int32)
                pos = np.zeros(B, np.int32)
                seq_ids = []
                for i, r in enumerate(reqs):
                    block[i, 0] = r.generated[-1]
                    block[i, 1:] = drafts[i]
                    pos[i] = self.cache.length(r.seq_id)
                    seq_ids.append(r.seq_id)
                if npad:
                    self.cache.truncate(_PAD_SEQ, 0)
                    seq_ids.extend([_PAD_SEQ] * npad)
                sampling = (self._spec_sampling_for(reqs, B)
                            if self.sample_on_device else None)
                self._count_dispatch("verify")
                out, accept = self._decoder.verify(
                    self.cache, seq_ids, block, pos, sampling=sampling)
                self._check_wedged(t0)
        finally:
            self._step_started_at = None
        _last_step_ts.set(time.time())
        rows: List[_SpecRow] = []
        for i, r in enumerate(reqs):
            a = int(accept[i])
            new_len = int(pos[i]) + a + 1
            # page-granular partial rollback: rejected positions'
            # lengths unwind on BOTH caches; their pages stay mapped
            # (inside the admission reservation) and their slots are
            # simply rewritten by later steps
            self.cache.truncate(r.seq_id, new_len)
            if r.use_draft:
                self.draft_cache.truncate(r.seq_id, new_len)
            rows.append(_SpecRow(out[i], a, drafts[i]))
        self._last_spec = (k * len(d_idx),
                           sum(int(accept[i]) for i in d_idx))
        if d_idx:
            _spec_proposed.inc(k * len(d_idx))
            _spec_accepted.inc(sum(int(accept[i]) for i in d_idx))
            rejected = 0
            for i in d_idx:
                _spec_accept_len.observe(int(accept[i]))
                rejected += int(accept[i]) < k
            if rejected:
                _spec_rollback.inc(rejected)
        _spec_draft_pages.set(self.draft_cache.pinned_pages)
        return rows

    def _exec_step(self, reqs) -> List[np.ndarray]:
        """Run ONE compiled decode step for ``reqs`` (all of, or a
        bisected subset of, the active batch), padded to a bucket.
        Resets ``_last_spec`` — a plain step proposes nothing.
        Tokens, positions and sampling counters are derived from
        request/cache state — a rolled-back step therefore replays
        IDENTICALLY (same threefry counters → same draws), which the
        retry/bisect recovery depends on.  Returns one output row per
        request (sampled token id, or the logits row).  With a draft
        model and at least one opted-in row the step runs SPECULATIVELY
        (one propose scan + one verify dispatch, multiple tokens per
        row) and the rows are :class:`_SpecRow`."""
        if self._spec and any(r.use_draft for r in reqs):
            return self._exec_spec_step(reqs)
        self._last_spec = (0, 0)
        B = self._bucket(len(reqs))
        npad = B - len(reqs)
        # the new token enters the sequence now: its rope position
        # (== current length) is read before the write
        tokens = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        seq_ids = []
        for i, r in enumerate(reqs):
            tokens[i, 0] = r.generated[-1]
            pos[i] = self.cache.length(r.seq_id)
            seq_ids.append(r.seq_id)       # decoder.step allocates pages
        # pad rows: a scratch sequence rewrites its slot 0 every step;
        # its page PERSISTS across steps (no allocate/free churn) and is
        # released only when the engine drains
        if npad:
            # truncate FIRST: the pad length advanced once per pad row
            # last step, and allocating against that stale length could
            # demand a second page once max_batch > page_size — the
            # scratch sequence must only ever hold its one headroom page
            self.cache.truncate(_PAD_SEQ, 0)
            self.cache.allocate(_PAD_SEQ, 1)   # no-op while already held
            seq_ids.extend([_PAD_SEQ] * npad)
        sampling = (self._sampling_for(reqs, pos + 1)
                    if self.sample_on_device else None)
        # ONE compiled program per step attempt for the whole subset
        # (per-row positions, pools donated through the step); with
        # on-device sampling the result is (B,) token ids — the only
        # per-step device->host transfer.  A wedge flag raised against
        # an earlier dispatch is stale here — drop it
        self._wedged.clear()
        t0 = self._step_started_at = time.monotonic()
        try:
            _faults.maybe_fire("decode_step", seq_ids=seq_ids[:len(reqs)])
            _faults.maybe_fire("engine_wedge",
                               seq_ids=seq_ids[:len(reqs)])
            with monitor.span("engine/decode_step",
                              histogram=_decode_step_s):
                self._count_dispatch("decode")
                out_np = self._decoder.step(self.cache, seq_ids, tokens,
                                            pos, sampling=sampling)
                self._check_wedged(t0)
        finally:
            self._step_started_at = None
        _last_step_ts.set(time.time())
        return [out_np[i] for i in range(len(reqs))]

    def _rollback_step(self, reqs, lens_before) -> None:
        """Restore pre-step cache lengths after a failed attempt (the
        decoder also rolls back its own advance; this covers faults
        fired before the decoder ran).  Pages stay mapped — they are
        inside the admission reservation and the replay rewrites their
        slots.  Speculative steps unwind the DRAFT cache too (the
        propose scan may have advanced it before the verify failed)."""
        for r in reqs:
            tgt, dft = lens_before[r.seq_id]
            self.cache.truncate(r.seq_id, tgt)
            if dft is not None and self._spec:
                self.draft_cache.truncate(r.seq_id, dft)

    def _step_isolated(self, reqs, lens_before):
        """(survivors, rows, poisoned) for one logical decode step:
        try the whole batch; on failure retry once (transient faults —
        the common TPU case after a preemption blip), then bisect to
        isolate the poisoned sequence(s) instead of erroring everyone
        (the old ``_fail_all`` blast radius)."""
        try:
            return reqs, self._exec_step(reqs), []
        except BaseException as e:  # noqa: BLE001 — classified below
            self._rollback_step(reqs, lens_before)
            # ISSUE 8: a REAL donated-buffer loss (or a watchdog-
            # flagged wedge) zeroed every sequence's KV — replay the
            # survivors so the retry below replays the step EXACTLY
            # instead of decoding over zeroed pages.  A row whose OWN
            # replay failed is dropped from the retry and quarantined.
            live, poisoned = self._split_replay_dead(
                reqs, self._after_step_failure(e, in_step=True))
            _decode_retries.inc()
            if not live:
                return [], [], poisoned
            try:
                return live, self._exec_step(live), poisoned
            except BaseException as e2:  # noqa: BLE001
                self._rollback_step(live, lens_before)
                live, dead2 = self._split_replay_dead(
                    live, self._after_step_failure(e2, in_step=True))
                poisoned += dead2
                if not live:
                    return [], [], poisoned
                s, o, p = self._bisect_step(live, lens_before, e2)
                return s, o, p + poisoned

    @staticmethod
    def _split_replay_dead(reqs, dead):
        """(live, quarantined) partition of ``reqs`` around the
        replay-failure set ``dead`` — each dead row counts as a
        quarantine (its error was set by the failed replay)."""
        if not dead:
            return list(reqs), []
        dead_ids = {id(r) for r in dead}
        live, out = [], []
        for r in reqs:
            if id(r) in dead_ids:
                _note_quarantine(r)
                out.append(r)
            else:
                live.append(r)
        return live, out

    def _bisect_step(self, reqs, lens_before, error):
        """Deterministic fault isolation: halve the failing batch and
        replay each half (solo replay at size 1).  Healthy halves
        advance their token normally; a size-1 failure quarantines that
        request with the error that killed it.  O(k·log n) extra step
        attempts for k poisoned sequences in a batch of n."""
        if len(reqs) == 1:
            r = reqs[0]
            r.error = error
            _note_quarantine(r)
            return [], [], [r]
        mid = (len(reqs) + 1) // 2
        survivors, rows, poisoned = [], [], []
        for half in (reqs[:mid], reqs[mid:]):
            # a row whose KV replay failed during a SIBLING subset's
            # recovery carries its error already — never step it again
            # (the _decode_step sweep retires it)
            half = [r for r in half if r.error is None]
            if not half:
                continue
            try:
                _decode_retries.inc()
                half_rows = self._exec_step(half)
            except BaseException as e:  # noqa: BLE001
                self._rollback_step(half, lens_before)
                # a device-side failure in THIS half also zeroed the
                # other half's (possibly already-advanced) KV: replay
                # everyone to their current lengths before probing on
                live, dead = self._split_replay_dead(
                    half, self._after_step_failure(e, in_step=True))
                poisoned.extend(dead)
                if live:
                    s, o, p = self._bisect_step(live, lens_before, e)
                    survivors.extend(s)
                    rows.extend(o)
                    poisoned.extend(p)
            else:
                survivors.extend(half)
                rows.extend(half_rows)
        return survivors, rows, poisoned

    def _decode_step(self):
        """One token for every active sequence, padded to a bucket;
        failures are isolated per sequence (retry, then bisect) rather
        than erroring the whole batch."""
        active = self._active
        lens_before = {
            r.seq_id: (self.cache.length(r.seq_id),
                       (self.draft_cache.length(r.seq_id)
                        if self._spec and r.use_draft else None))
            for r in active}
        jlens = ({id(r): len(r.generated) for r in active}
                 if self.journal is not None else None)
        for r in active:
            r.generated.append(r.next_token)
        _active_seqs.set(len(active))
        _batch_occupancy.observe(len(active) / self.max_batch)
        # the gauge is process-global (last constructor wins), so the
        # engine doing the decoding re-asserts its mode every step —
        # a live server's /metrics stays truthful even after another
        # engine (bench baseline, parity test) was built in-process
        _sampling_on_device_g.set(int(self.sample_on_device))
        on_device = self.sample_on_device
        t_tr = _tracer.now_ns() if _tracer.enabled else 0
        survivors, rows, poisoned = self._step_isolated(active, lens_before)
        if _tracer.enabled and t_tr:
            # the engine-step ring (ISSUE 10): batch composition per
            # class + spec economics + the dispatch wall time (retries
            # and bisection probes included — that IS this step's cost;
            # t_tr == 0 = window opened mid-dispatch, skip the slice)
            comp: dict = {}
            for r in active:
                comp[r.priority] = comp.get(r.priority, 0) + 1
            prop, acc = self._last_spec
            _tracer.step_record(
                "decode", self.steps, t_tr, _tracer.now_ns(),
                batch=len(active), classes=comp, spec_proposed=prop,
                spec_accepted=acc, poisoned=len(poisoned),
                requests=[r.request_id for r in active])
        # ISSUE 8 replay-failure sweep: a row whose KV replay failed
        # during recovery carries its error.  The failing subset's own
        # dead rows are already in `poisoned`; one that died OUTSIDE
        # that scope — its bisect half had already succeeded, or was
        # still pending — must be ejected HERE, never left decoding
        # over a half-reconstructed cache.  Executed-token rows retire
        # without the pop; un-stepped rows join the poisoned path.
        dead_done: List[_Request] = []
        if any(r.error is not None for r in survivors):
            pairs = list(zip(survivors, rows))
            survivors, rows = [], []
            for r, row in pairs:
                if r.error is not None:
                    _note_quarantine(r)
                    dead_done.append(r)
                else:
                    survivors.append(r)
                    rows.append(row)
        accounted = ({id(r) for r in survivors}
                     | {id(r) for r in poisoned}
                     | {id(r) for r in dead_done})
        for r in active:
            if id(r) not in accounted and not r.done.is_set() \
                    and r.error is not None:
                _note_quarantine(r)
                poisoned.append(r)
        _tokens_total.inc(len(survivors))

        # request-local state (r.*) is scheduler-thread-owned: decide
        # retirements and sample next tokens OUTSIDE the lock, then take
        # the lock for the shared-state transition (pages/reservations/
        # active list) — the discipline tpu_lint TPL004 enforces
        still, retired = [], []
        accepted_emitted = 0
        for r, row in zip(survivors, rows):
            if _tracer.enabled:
                if isinstance(row, _SpecRow):
                    _tracer.request_event(
                        r.request_id, "verify_step", step=self.steps,
                        accept=int(row.accept))
                else:
                    _tracer.request_event(r.request_id, "decode_step",
                                          step=self.steps)
            eos_hit = (r.eos_token_id is not None
                       and r.generated[-1] == r.eos_token_id)
            if eos_hit or len(r.generated) >= r.max_new_tokens:
                retired.append(r)
                continue
            if isinstance(row, _SpecRow):
                # consume the accepted draft tokens SEQUENTIALLY, with
                # the same eos/budget checks the plain path applies one
                # step at a time — so speculative output is, token for
                # token, what target-only greedy would have emitted
                done = False
                for t in row.drafts[:row.accept]:
                    r.generated.append(int(t))
                    accepted_emitted += 1
                    if (r.eos_token_id is not None
                            and int(t) == r.eos_token_id) \
                            or len(r.generated) >= r.max_new_tokens:
                        done = True
                        break
                if done:
                    retired.append(r)
                    continue
                out_row = row.out
            else:
                out_row = row
            r.next_token = (int(out_row) if on_device
                            else self._pick(r, out_row))
            still.append(r)
        if accepted_emitted:
            _tokens_total.inc(accepted_emitted)
        if self.journal is not None:
            # one journal row per CONTINUING request: the tokens this
            # step committed plus the new pending sample.  Retiring
            # rows need no emission — their retire record (below, via
            # _retire_locked) drops them from the live set, and a
            # crash before that record replays their last step
            # bit-identically anyway.
            for r in still:
                self._jrows.append(
                    (r.request_id, list(r.generated[jlens[id(r)]:]),
                     r.next_token))
        for r in poisoned:
            # the token recorded for this step never executed
            r.generated.pop()
        with self._cond:
            self.steps += 1
            for r in retired:
                self._retire_locked(r)
            for r in poisoned:
                self._retire_locked(r)
            for r in dead_done:
                self._retire_locked(r)
            self._active = still
            if not still:
                # idle: the scratch page goes back too, so a drained
                # engine reports a fully reclaimed pool — released
                # BEFORE waking the retired requests' waiters, who may
                # assert exactly that
                self._free_pads_locked()
            self._cond.notify_all()        # drain() waits on this
        _active_seqs.set(len(still))
        for r in retired:
            r.done.set()
        for r in poisoned:
            r.done.set()
        for r in dead_done:
            r.done.set()

    def _fail_all(self, exc):
        """LAST-RESORT scheduler-fault handler (isolation failed or the
        fault was outside any step): error out every in-flight request
        WITHOUT leaking pool capacity — sequences that already own
        pages are freed and their reservations rolled back, so the
        engine stays usable."""
        with self._cond:
            queued = self._sched.pop_all()
            holders = self._active + self._prefilling + self._preempted
            for r in holders + queued:
                if r.done.is_set():
                    continue
                if r.finished_at is not None:
                    # retired successfully earlier THIS step (its
                    # done.set() is deferred to the end of _decode_step):
                    # deliver the completed generation, don't error it
                    r.done.set()
                    continue
                r.error = exc
                self._cache_result_locked(r)
                # the error IS delivered to the waiter — terminal, so
                # the journal must not resurrect it after a restart
                self._journal_retire(r)
                r.done.set()
            for r in holders:
                if r.seq_id is not None:
                    self.cache.free(r.seq_id)
                    if self._spec:
                        self.draft_cache.free(r.seq_id)
                    r._draft_reserved = False
            self._free_pads_locked()
            self._reserved_pages = self._pad_pages   # only pad headroom
            self._reserved_draft_pages = self._pad_pages
            self._active = []
            self._prefilling = []
            self._preempted = []
            _active_seqs.set(0)
            _queue_depth.set(0)
            self._cond.notify_all()

    def _loop(self):
        while True:
            with self._cond:
                while not self._stop and not len(self._sched) \
                        and not self._active and not self._prefilling \
                        and not self._preempted:
                    # brownout is a property of LOAD: an engine with
                    # nothing queued and nothing running is not
                    # browned out, whatever the ladder last latched —
                    # without this, a drained engine would keep
                    # shedding the first arrivals of the next burst
                    if self._brownout:
                        self._set_brownout_locked(0, 0.0)
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    self._free_pads_locked()
                    stopped = (self._sched.pop_all() + self._prefilling
                               + self._preempted + self._active)
                    self._prefilling = []
                    self._preempted = []
                    self._active = []
                    for r in stopped:
                        r.error = RuntimeError("engine stopped")
                        self._cache_result_locked(r)
                        r.done.set()
                    return
            try:
                with self._cond:
                    reaped = self._reap_locked()
                    # closed-loop overload protection (ISSUE 19): one
                    # controller evaluation per iteration — the ladder
                    # first (its level gates this iteration's sheds),
                    # then the TPOT trigger (its freed slot is visible
                    # to the admission pass below)
                    self._update_brownout_locked()
                    self._tpot_preempt_locked()
                    self._admit_locked()
                    plan = self._plan_chunks_locked()
                    # snapshot barrier (ISSUE 8): a waiting snapshot()
                    # reads its consistent between-steps cut before the
                    # next device batch opens (the wait releases the
                    # lock; nothing below mutates what was planned)
                    while self._snap_waiters and not self._stop:
                        self._cond.wait(0.1)
                    self._stepping = bool(plan) or bool(self._active)
            except BaseException as e:  # noqa: BLE001 — scheduler fault
                # a bug in admission/reaping must fail the in-flight
                # requests LOUDLY, never kill this thread silently and
                # leave every waiter blocked on a dead engine
                self._fail_all(e)
                continue
            for r in reaped:
                r.done.set()
            # TPOT signal (ISSUE 19): for an active row one iteration
            # is one output token, so the whole iteration's wall time —
            # chunks included — is the per-token latency the budget is
            # judged against.  Scheduler-thread only, like _disp_n.
            had_active = bool(self._active)
            t_iter = time.perf_counter()
            try:
                if self._legacy_iteration():
                    # legacy composition: at most ~a chunk budget of
                    # prefill dispatches, then ONE decode step for
                    # everything active (ISSUE 7 interleaving);
                    # per-chunk failures quarantine only their own
                    # request (ISSUE 4 discipline carried over)
                    self._run_chunks(plan)     # device work: outside lock
                    if self._active:
                        self._decode_step()
                else:
                    # unified ragged step (ISSUE 17): the chunk plan's
                    # spans + every active row in ONE compiled dispatch
                    if self.prefill_chunk_tokens is None and plan:
                        # unchunked: full-prompt spans would give the
                        # ragged program an unbounded (rows, max-span)
                        # bucket space — every novel prompt length a
                        # recompile.  Keep whole-prompt prefill on the
                        # legacy length-bucketed program and fold only
                        # the active rows (span 1 or k+1: bounded)
                        # into the ragged dispatch.
                        self._run_chunks(plan)
                        plan = ()
                    self._unified_step(plan)
            except BaseException as e:  # noqa: BLE001 — fail loudly, not hang
                self._fail_all(e)
            finally:
                if had_active:
                    dt = time.perf_counter() - t_iter
                    self._step_ewma = (dt if self._step_ewma is None
                                       else 0.7 * self._step_ewma
                                       + 0.3 * dt)
                # ISSUE 13: the iteration's coalesced journal record —
                # admitted ids + per-row emissions — enqueued ONCE per
                # loop pass (rows for requests _fail_all just retired
                # are ignored at replay: their retire precedes them)
                self._journal_flush_step()
                if self._stepping:
                    with self._cond:
                        self._stepping = False
                        self._cond.notify_all()
