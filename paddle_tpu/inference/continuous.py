"""Continuous batching over the paged-KV pool.

Reference capability: the block-multi-head serving path
(paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu) —
sequences share a page pool and join/leave the running decode batch per
step.  The round-4 GenerationServer serialized whole requests behind a
lock; this engine admits each sequence independently:

  * requests enqueue; a scheduler thread admits them whenever a running
    slot and enough pool pages are free (admission RESERVES the
    sequence's worst-case pages so mid-decode allocation can never fail
    and wedge the batch);
  * every decode step runs ALL active sequences as one batch — each at
    its own length/position (per-row rope positions, per-row page
    tables), so a long generation no longer blocks short ones behind it;
  * finished sequences retire per step (pages freed, waiter woken) and
    their slots are immediately re-admissible.

Batch shapes are bucketed to powers of two (padding rows ride on a
scratch sequence that is truncated every step) so the decode step
compiles once per bucket, not once per active-count.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, List, Optional

import numpy as np
from .. import monitor
from ..ops.pallas.paged_attention import PagedKVCache

__all__ = ["ContinuousBatchingEngine"]

_PAD_SEQ = "__pad__"

# engine telemetry (ISSUE 1): the serving-side numbers the ROADMAP's
# "serve heavy traffic" goal is judged by
_queue_depth = monitor.gauge(
    "inference_queue_depth", "sequences waiting for admission")
_active_seqs = monitor.gauge(
    "inference_active_sequences", "sequences in the running decode batch")
_batch_occupancy = monitor.histogram(
    "inference_batch_occupancy", "active/max_batch fraction per decode "
    "step", buckets=tuple(i / 8 for i in range(1, 9)))
_decode_step_s = monitor.histogram(
    "decode_step_seconds", "one continuous-batching decode step")
_prefill_s = monitor.histogram(
    "prefill_seconds", "one sequence's prefill")
_tokens_total = monitor.counter(
    "generated_tokens_total", "tokens produced by the decode loop")
_ttft_s = monitor.histogram(
    "time_to_first_token_seconds", "submit -> first sampled token")
_gen_latency_s = monitor.histogram(
    "generate_latency_seconds", "submit -> sequence retirement")
# serving hot-path telemetry (ISSUE 2): prefix-cache effectiveness and
# the on-device-sampling mode flag
_prefix_lookups = monitor.counter(
    "prefix_cache_lookups_total", "admissions that consulted the prefix "
    "cache")
_prefix_hits = monitor.counter(
    "prefix_cache_hits_total", "admissions whose prompt shared a cached "
    "page-aligned prefix")
_prefix_hit_tokens = monitor.counter(
    "prefix_cache_hit_tokens_total", "prompt tokens served from cached "
    "prefix pages instead of being re-prefilled")
_sampling_on_device_g = monitor.gauge(
    "sampling_on_device", "1 when the engine samples inside the compiled "
    "step (host transfer is (batch,) ids), 0 on the host-logits path")


class _Request:
    """One sequence's life in the engine."""

    def __init__(self, prompt, max_new_tokens, eos_token_id, do_sample,
                 temperature, seed):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        self.max_new_tokens = int(max_new_tokens)
        self.eos_token_id = eos_token_id
        self.do_sample = bool(do_sample)
        self.temperature = float(temperature)
        self.seed = int(seed) & 0xFFFFFFFF   # on-device threefry seed
        self.rng = np.random.default_rng(seed)
        self.prefix_tokens = 0               # prompt tokens shared at admit
        self.generated: List[int] = []
        self.next_token: Optional[int] = None   # sampled, not yet decoded
        self.seq_id: Optional[int] = None
        self.done = threading.Event()
        self.error: Optional[BaseException] = None
        self.submitted_at = time.perf_counter()
        self.first_token_at: Optional[float] = None
        self.finished_at: Optional[float] = None

    @property
    def output_ids(self) -> np.ndarray:
        return np.concatenate(
            [self.prompt, np.asarray(self.generated, np.int32)])

    def result(self, timeout=None) -> np.ndarray:
        if not self.done.wait(timeout):
            raise TimeoutError("generation still running")
        if self.error is not None:
            raise self.error
        return self.output_ids


class ContinuousBatchingEngine:
    """Scheduler + decode loop over one shared PagedKVCache.

    ``submit`` is thread-safe and non-blocking; ``generate`` is the
    blocking batch facade with PagedGenerator's signature.

    Hot-path defaults (ISSUE 2): ``sample_on_device`` fuses greedy
    argmax + temperature sampling into the compiled step, so each
    decode step transfers (batch,) int32 ids instead of the full
    (batch, vocab) logits; ``prefix_cache`` keeps retired prompts'
    page-aligned prefix KV resident (refcounted, LRU-evicted under
    pool pressure) so a request sharing a cached prefix maps those
    pages read-only and prefills only its suffix.
    """

    def __init__(self, model, total_pages: int = 512, page_size: int = 16,
                 max_batch: int = 8, sample_on_device: bool = True,
                 prefix_cache: bool = True):
        self.model = model
        self.max_batch = int(max_batch)
        self.max_position = int(model.config.max_position_embeddings)
        self.sample_on_device = bool(sample_on_device)
        self.prefix_cache = bool(prefix_cache)
        _sampling_on_device_g.set(int(self.sample_on_device))
        # runtime mirror of the analysis auditor's recompile rules:
        # every XLA compile the decode loop triggers shows up in
        # jit_recompile_count (steady-state serving should sit at zero)
        monitor.install_compile_hooks()
        self.cache = PagedKVCache.from_model(
            model, total_pages=total_pages, page_size=page_size)
        from .paged import JittedPagedDecoder
        self._decoder = JittedPagedDecoder(model)
        # one scratch sequence backs every padding row of every bucket;
        # its single page stays allocated WHILE sequences are active
        # (the old allocate/truncate/free per padded step churned the
        # free list under the pool lock) and is released whenever the
        # engine goes idle, so an idle engine still reports a fully
        # reclaimed pool; admission arithmetic always reserves 1 page
        # for it either way
        self._reserved_pages = 1               # headroom for the pad page
        self._queue: Deque[_Request] = deque()
        self._active: List[_Request] = []
        self._cond = threading.Condition()
        self._stop = False
        self._next_seq = 0
        self.steps = 0                          # decode steps executed
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ------------------------------------------------------------- public
    def submit(self, prompt, max_new_tokens: int = 32,
               eos_token_id: Optional[int] = None, do_sample: bool = False,
               temperature: float = 1.0, seed: int = 0) -> _Request:
        req = _Request(prompt, max_new_tokens, eos_token_id, do_sample,
                       temperature, seed)
        total = len(req.prompt) + req.max_new_tokens
        if total > self.max_position:
            # past the rope table the gather would silently clamp and
            # reuse the last angles (the scalar path raises; so do we)
            raise ValueError(
                f"prompt + max_new_tokens = {total} exceeds the model's "
                f"max_position_embeddings ({self.max_position})")
        need = self._pages_for(req)
        if need > self.cache.total_pages - 1:
            raise RuntimeError(
                f"request needs {need} pages but the pool holds "
                f"{self.cache.total_pages} total; grow total_pages")
        with self._cond:
            if self._stop:
                raise RuntimeError("engine stopped")
            self._queue.append(req)
            _queue_depth.set(len(self._queue))
            self._cond.notify_all()
        return req

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 seed: int = 0):
        """Blocking batch API (PagedGenerator-compatible): submits each
        row as its own sequence and eos-pads rows to a common length."""
        ids = np.asarray(input_ids, np.int32)
        reqs = [self.submit(row, max_new_tokens, eos_token_id, do_sample,
                            temperature, seed + i)
                for i, row in enumerate(ids)]
        rows = [r.result() for r in reqs]
        width = max(len(r) for r in rows)
        pad = 0 if eos_token_id is None else eos_token_id
        out = np.full((len(rows), width), pad, np.int32)
        for i, r in enumerate(rows):
            out[i, :len(r)] = r
        return out

    def stop(self):
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ---------------------------------------------------------- scheduler
    def _pages_for(self, req) -> int:
        ps = self.cache.page_size
        return -(-(len(req.prompt) + req.max_new_tokens) // ps)

    def _pop_admissible_locked(self) -> List[_Request]:
        """Caller holds ``self._cond`` (the ``_locked`` suffix is the
        lint-checked contract — tpu_lint's TPL004 exempts these helpers
        and flags any other off-lock engine-state mutation).
        Move queued requests to 'admitted' while slots
        and reserved pages allow, assigning seq ids and RESERVING their
        worst-case pages (prompt + full max_new_tokens) so decode-time
        allocate() can never exhaust the pool.  A prompt whose prefix is
        already cached ACQUIRES the shared pages here (pinning them
        against eviction) and reserves only what the pool must newly
        provide: the un-shared pages plus whichever shared pages were
        not already pinned by another live sharer — shared pages are
        counted once across the engine, not once per sharer.  Prefill
        itself runs outside the lock — submit() must never wait on
        device work."""
        admitted = []
        while self._queue and len(self._active) + len(admitted) < self.max_batch:
            req = self._queue[0]
            shared_tok, newly_pinned = (
                self.cache.probe_prefix(req.prompt) if self.prefix_cache
                else (0, 0))
            need = (self._pages_for(req)
                    - shared_tok // self.cache.page_size + newly_pinned)
            if self._reserved_pages + need > self.cache.total_pages:
                break                     # wait for a retirement
            self._queue.popleft()
            self._reserved_pages += need
            req.seq_id = self._next_seq
            self._next_seq += 1
            if shared_tok:
                got = self.cache.acquire_prefix(req.seq_id, req.prompt)
                assert got == shared_tok   # nothing ran between probe/acquire
                req.prefix_tokens = got
            admitted.append(req)
        _queue_depth.set(len(self._queue))
        return admitted

    def _sampling_for(self, reqs, ctrs):
        """(seeds, ctrs, temps, flags) arrays for the fused on-device
        sampler, padded to ``len(ctrs)`` rows (pad rows draw nothing:
        flags False).  ``ctrs`` is each row's absolute token position —
        the replay-stable per-draw counter."""
        n = len(ctrs)
        seeds = np.zeros(n, np.uint32)
        temps = np.ones(n, np.float32)
        flags = np.zeros(n, bool)
        for i, r in enumerate(reqs):
            seeds[i] = r.seed
            temps[i] = max(r.temperature, 1e-6)
            flags[i] = r.do_sample
        return seeds, np.asarray(ctrs, np.int32), temps, flags

    def _prefill(self, req):
        # bucketed compiled prefill: one compile per power-of-two prompt
        # (or suffix) length, not one per distinct length
        k = req.prefix_tokens
        sampling = (self._sampling_for([req], [len(req.prompt)])
                    if self.sample_on_device else None)
        with monitor.span("engine/prefill", histogram=_prefill_s):
            if k:
                out = self._decoder.prefix_prefill(
                    self.cache, [req.seq_id], req.prompt[None, k:],
                    prefix_tokens=k, bucket=True, sampling=sampling)
            else:
                out = self._decoder.prefill(
                    self.cache, [req.seq_id], req.prompt[None],
                    bucket=True, sampling=sampling)
        if self.prefix_cache:
            _prefix_lookups.inc()
            if k:
                _prefix_hits.inc()
                _prefix_hit_tokens.inc(k)
            # retain this prompt's page-aligned prefixes for later
            # sharers (idempotent for the pages it itself shared)
            self.cache.register_prefix(req.seq_id, req.prompt)
        req.next_token = (int(out[0]) if sampling is not None
                          else self._pick(req, out[0]))
        req.first_token_at = time.perf_counter()
        _ttft_s.observe(req.first_token_at - req.submitted_at)

    def _pick(self, req, logits_row) -> int:
        from .paged import sample_token
        return sample_token(logits_row, req.do_sample, req.temperature,
                            req.rng)

    def _retire_locked(self, req):
        """Caller holds ``self._cond``.  Release the request's pages and
        exactly the reservation its retirement uncovers: the worst-case
        pages it never allocated, plus each held page that stopped being
        pinned (a shared page another live sharer still maps keeps its
        reservation — it transfers to that sharer's accounting)."""
        slack = (self._pages_for(req)
                 - len(self.cache._seq_pages.get(req.seq_id, ())))
        released = self.cache.free(req.seq_id)
        self._reserved_pages -= slack + released
        req.finished_at = time.perf_counter()
        _gen_latency_s.observe(req.finished_at - req.submitted_at)

    def _bucket(self, n: int) -> int:
        from .paged import next_pow2
        return min(next_pow2(n), self.max_batch)

    def _decode_step(self):
        """One token for every active sequence, padded to a bucket."""
        active = self._active
        B = self._bucket(len(active))
        npad = B - len(active)
        # the new token enters the sequence now: record it first so its
        # rope position (== current length) is read before the write
        tokens = np.zeros((B, 1), np.int32)
        pos = np.zeros(B, np.int32)
        seq_ids = []
        for i, r in enumerate(active):
            r.generated.append(r.next_token)
            tokens[i, 0] = r.next_token
            pos[i] = self.cache.length(r.seq_id)
            seq_ids.append(r.seq_id)       # decoder.step allocates pages
        # pad rows: a scratch sequence rewrites its slot 0 every step;
        # its page PERSISTS across steps (no allocate/free churn) and is
        # released only when the engine drains
        if npad:
            # truncate FIRST: the pad length advanced once per pad row
            # last step, and allocating against that stale length could
            # demand a second page once max_batch > page_size — the
            # scratch sequence must only ever hold its one headroom page
            self.cache.truncate(_PAD_SEQ, 0)
            self.cache.allocate(_PAD_SEQ, 1)   # no-op while already held
            seq_ids.extend([_PAD_SEQ] * npad)
        _active_seqs.set(len(active))
        _batch_occupancy.observe(len(active) / self.max_batch)
        # the gauge is process-global (last constructor wins), so the
        # engine doing the decoding re-asserts its mode every step —
        # a live server's /metrics stays truthful even after another
        # engine (bench baseline, parity test) was built in-process
        _sampling_on_device_g.set(int(self.sample_on_device))
        on_device = self.sample_on_device
        sampling = (self._sampling_for(active, pos + 1) if on_device
                    else None)
        # ONE compiled program per decode step for the whole running
        # batch (per-row positions, pools donated through the step);
        # with on-device sampling the result is (B,) token ids — the
        # only per-step device->host transfer
        with monitor.span("engine/decode_step", histogram=_decode_step_s):
            out_np = self._decoder.step(self.cache, seq_ids, tokens,
                                        pos, sampling=sampling)
        _tokens_total.inc(len(active))

        # request-local state (r.*) is scheduler-thread-owned: decide
        # retirements and sample next tokens OUTSIDE the lock, then take
        # the lock for the shared-state transition (pages/reservations/
        # active list) — the discipline tpu_lint TPL004 enforces
        still, retired = [], []
        for i, r in enumerate(active):
            eos_hit = (r.eos_token_id is not None
                       and r.generated[-1] == r.eos_token_id)
            if eos_hit or len(r.generated) >= r.max_new_tokens:
                retired.append(r)
                continue
            r.next_token = (int(out_np[i]) if on_device
                            else self._pick(r, out_np[i]))
            still.append(r)
        with self._cond:
            self.steps += 1
            for r in retired:
                self._retire_locked(r)
            self._active = still
            if not still:
                # idle: the scratch page goes back too, so a drained
                # engine reports a fully reclaimed pool — released
                # BEFORE waking the retired requests' waiters, who may
                # assert exactly that
                self.cache.free(_PAD_SEQ)
        _active_seqs.set(len(still))
        for r in retired:
            r.done.set()

    def _fail_all(self, exc, admitted):
        """Error out every in-flight request WITHOUT leaking pool
        capacity: sequences that already own pages are freed and their
        reservations rolled back, so the engine stays usable."""
        with self._cond:
            for r in self._active + admitted + list(self._queue):
                if r.done.is_set():
                    continue
                if r.finished_at is not None:
                    # retired successfully earlier THIS step (its
                    # done.set() is deferred to the end of _decode_step):
                    # deliver the completed generation, don't error it
                    r.done.set()
                    continue
                r.error = exc
                r.done.set()
            for r in self._active + admitted:
                if r.seq_id is not None:
                    self.cache.free(r.seq_id)
            self.cache.free(_PAD_SEQ)
            self._reserved_pages = 1          # only the pad headroom
            self._active, self._queue = [], deque()
            _active_seqs.set(0)
            _queue_depth.set(0)

    def _loop(self):
        while True:
            with self._cond:
                while not self._stop and not self._queue and not self._active:
                    self._cond.wait(timeout=0.5)
                if self._stop:
                    self.cache.free(_PAD_SEQ)
                    for r in list(self._queue) + self._active:
                        r.error = RuntimeError("engine stopped")
                        r.done.set()
                    return
                admitted = self._pop_admissible_locked()
            try:
                for req in admitted:           # device work: outside lock
                    self._prefill(req)
                with self._cond:
                    self._active.extend(admitted)
                    admitted = []
                if self._active:
                    self._decode_step()
            except BaseException as e:  # noqa: BLE001 — fail loudly, not hang
                self._fail_all(e, admitted)
