"""Fault-tolerant serving fleet: replica supervisor + health-gated
router with journal-backed failover (ISSUE 14).

Everything below the router was built to be fronted — per-replica
429/503 + Retry-After, graceful drain, quarantine, snapshot/restore and
the PR 13 write-ahead request journal — and this module is the layer
that survives a *replica* dying, not just a request or a buffer:

  * :class:`ReplicaSupervisor` owns N ``GenerationServer`` replicas
    (each with its OWN journal directory), probes their ``/health`` on
    a fixed cadence, registers one liveness heartbeat per replica with
    the comm watchdog (``distributed/watchdog.py`` — a replica that
    stops answering fires the same timeout machinery as a hung
    collective), and on replica death runs **journal-backed failover**:
    the dead replica's write-ahead journal is recovered on the
    supervisor, its live set (mid-stream requests: prompt, generated
    ids, pending next token, seed, class/tenant, draft opt-in,
    deadlines — never KV) is MIGRATED to surviving replicas through
    the existing ``restore(strict=False)`` admission path (the
    ``POST /admin/migrate`` far side), and the migrated ids are retired
    in the source journal so a restarted replica over the same
    directory cannot double-execute them.  Because the replay primitive
    is bit-exact for greedy AND sampled rows (PR 8/13), a stream
    resumes token-for-token on a *different* replica; page-provenance
    records (``pages``, ISSUE 14 satellite) group migrating sharers by
    their prefix's stable content key so the destination's prefix index
    warms once.

  * :class:`FleetRouter` is the HTTP front (``/generate`` / ``/health``
    / ``/metrics`` / ``/result/<id>``) with the robustness kit:

      - **per-replica circuit breaker** — ``breaker_threshold``
        consecutive transport/5xx failures open the circuit
        (``router_circuit_open``); after ``breaker_reset_s`` it goes
        half-open and admits exactly ONE probe request, whose outcome
        closes or re-opens it;
      - **bounded admission retry** with exponential backoff + seeded
        jitter, IDEMPOTENT by ``request_id``: the router pins an id on
        every forwarded request, so a retried admit that actually
        landed is rejected by the far engine ("already live") and the
        router re-attaches through ``/result/<id>`` instead of running
        the request twice;
      - **backpressure aggregation** — when every healthy replica is
        saturated the fleet replies 429 with ``Retry-After`` = min over
        the healthy replicas' ``retry_after_hint``;
      - **drain-aware routing** — a replica whose ``/health`` reports
        ``"draining"`` receives no new work (in-flight generations on
        it still finish and remain ``/result``-reachable);
      - **cross-replica ``/result/<id>``** — routed to the replica that
        owns the id (ownership follows migration), falling back to a
        fleet-wide scan, so a client's handle survives a failover as if
        nothing happened.

Series (all ``replica``-labeled, so two engines in one process stay
separated): ``fleet_replica_up``, ``fleet_failovers_total``,
``fleet_migrated_requests_total``, ``router_retries_total``,
``router_circuit_open``.

With in-process replicas (the default ``factory`` path) the process
shares ONE metrics registry, so the router's ``/metrics`` is the
aggregated fleet exposition; with external/subprocess replicas
(:meth:`ReplicaSupervisor.add_replica`) it exposes the router-side
series and each replica keeps serving its own ``/metrics``.

The scope contract (ROADMAP "Engine fleet"): this is the
router/robustness HALF of the fleet item — TP-sharding the compiled
programs over a mesh drops into an already-supervised fleet later.
"""
from __future__ import annotations

import json
import random
import threading
import time
import urllib.error
import urllib.request
import uuid
import warnings
from collections import OrderedDict
from http.server import ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from .. import monitor
from ..testing import faults as _faults
from .server import GenerationServer, _JsonHandler, _ServerLifecycle

__all__ = ["CircuitBreaker", "Replica", "ReplicaSupervisor",
           "FleetRouter", "FleetAutoscaler"]

# fleet telemetry (ISSUE 14): replica-labeled, so N engines in one
# process (the in-process supervisor mode) keep their series separated
_replica_up = monitor.gauge(
    "fleet_replica_up", "1 while the replica answers health probes "
    "(draining replicas still count as up), 0 once it is down/dead",
    ("replica",))
_failovers_total = monitor.counter(
    "fleet_failovers_total", "journal-backed failovers executed, "
    "labeled by the replica that died", ("replica",))
_migrated_total = monitor.counter(
    "fleet_migrated_requests_total", "in-flight requests migrated off "
    "a dead replica's recovered journal onto survivors", ("replica",))
_router_retries = monitor.counter(
    "router_retries_total", "admission attempts the router retried "
    "after a transport/5xx failure, labeled by the replica that "
    "failed the attempt", ("replica",))
_circuit_open = monitor.gauge(
    "router_circuit_open", "1 while the replica's admission circuit "
    "is open (consecutive-failure threshold crossed; half-open probes "
    "re-close it), else 0", ("replica",))
_scale_events = monitor.counter(
    "fleet_scale_events_total", "elastic replica-count changes made "
    "by the autoscaler (ISSUE 19): 'up' spawns a fresh replica when "
    "the fleet's queue/SLO pressure holds above the scale-up band, "
    "'down' drain-then-retires the newest surplus replica when load "
    "subsides", ("direction",))
_fleet_size_g = monitor.gauge(
    "fleet_size", "replicas the supervisor currently owns (DEAD "
    "replicas excluded)")
_scale_events.inc(0, direction="up")       # materialize the series
_scale_events.inc(0, direction="down")


def _http_json(url: str, body: Optional[dict] = None,
               timeout: float = 30.0):
    """One JSON round trip: ``(status, payload, headers)``.  HTTP error
    statuses are RETURNED (their JSON body parsed when present) —
    only transport-level failures raise, so callers can tell "the
    replica answered 429/503" from "the replica is gone"."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(
        url, data=data,
        headers={} if body is None else
        {"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}"), dict(
                r.headers)
    except urllib.error.HTTPError as e:
        try:
            payload = json.loads(e.read() or b"{}")
        except ValueError:
            payload = {}
        return e.code, payload, dict(e.headers or {})
    except urllib.error.URLError as e:
        # unwrap refused connections: "nothing is listening" is the
        # one transport failure that PROVES the request never landed,
        # and the router's retry ladder branches on exactly that
        if isinstance(e.reason, ConnectionRefusedError):
            raise e.reason
        raise


class CircuitBreaker:
    """Per-replica admission circuit (ISSUE 14 tentpole): CLOSED until
    ``threshold`` CONSECUTIVE failures open it; after ``reset_s`` it
    half-opens and :meth:`allow` admits exactly ONE probe request —
    that probe's outcome re-closes (success) or re-opens (failure) the
    circuit.  Thread-safe; the ``router_circuit_open`` gauge mirrors
    the state per replica label."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, name: str, threshold: int = 3,
                 reset_s: float = 1.0):
        self.name = name
        self.threshold = max(1, int(threshold))
        self.reset_s = float(reset_s)
        self._lock = threading.Lock()
        self._failures = 0
        self._state = self.CLOSED
        self._opened_at = 0.0
        self._probing = False
        _circuit_open.set(0, replica=name)

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May an admission attempt be sent to this replica right now?
        Half-open grants a single in-flight probe; its outcome must be
        reported back via record_success/record_failure."""
        now = time.monotonic()
        with self._lock:
            if self._state == self.CLOSED:
                return True
            if self._state == self.OPEN:
                if now - self._opened_at < self.reset_s:
                    return False
                self._state = self.HALF_OPEN
                self._probing = True
                return True
            # HALF_OPEN: one probe at a time
            if self._probing:
                return False
            self._probing = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            self._probing = False
            self._state = self.CLOSED
        _circuit_open.set(0, replica=self.name)

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            self._probing = False
            if (self._state == self.HALF_OPEN
                    or self._failures >= self.threshold):
                self._state = self.OPEN
                self._opened_at = time.monotonic()
        _circuit_open.set(int(self.state == self.OPEN),
                          replica=self.name)


class Replica:
    """One replica's handle: its address, journal directory and
    supervision state.  ``server`` is set for in-process replicas (the
    factory path), ``proc`` for subprocess ones (the chaos lane); both
    are probed and failed over identically — over HTTP."""

    #: state machine: STARTING -> UP <-> DRAINING; probe-failure
    #: threshold -> DOWN; failover marks DEAD (terminal until restart)
    STARTING, UP, DRAINING, DOWN, DEAD = (
        "starting", "up", "draining", "down", "dead")

    def __init__(self, name: str, url: str,
                 journal_dir: Optional[str] = None,
                 server: Optional[GenerationServer] = None,
                 proc=None, breaker_threshold: int = 3,
                 breaker_reset_s: float = 1.0):
        self.name = name
        self.url = url.rstrip("/")
        self.journal_dir = journal_dir
        self.server = server
        self.proc = proc
        self.state = self.STARTING
        self.created_at = time.monotonic()
        self.last_ok: Optional[float] = None
        self.probe_failures = 0
        self.retry_after_hint = 1
        self.health: dict = {}
        self.breaker = CircuitBreaker(name, breaker_threshold,
                                      breaker_reset_s)

    @property
    def routable(self) -> bool:
        """May NEW work be routed here?  Health-gated (up, not
        draining, not down/dead) — the breaker is consulted separately
        at attempt time so a half-open probe can still go through."""
        return self.state == self.UP

    def kill(self) -> None:
        """Hard-kill this replica (test/chaos hook).  Subprocess
        replicas get a real SIGKILL.  In-process replicas get the
        closest legal emulation: listener torn down, engine
        hard-stopped (which deliberately journals NO retirements —
        the PR 13 crash floor) and the journal closed with its live
        set intact, so the supervisor's failover recovers exactly what
        a ``kill -9`` would have left on disk."""
        if self.proc is not None:
            self.proc.kill()
            self.proc.wait(timeout=30)
        elif self.server is not None:
            try:
                self.server.stop()
            except Exception:  # noqa: BLE001 — dying is the point
                pass


class ReplicaSupervisor:
    """Owns the fleet's replicas: spawn, probe, heartbeat, failover.

    ``factory(name, journal_dir) -> GenerationServer`` builds one
    in-process replica (unstarted; the supervisor starts it on port 0
    and waits on its readiness signal — no sleep-and-poll).  Pass
    ``replicas=N`` with a factory, or skip the factory and register
    external/subprocess replicas via :meth:`add_replica`.

    Liveness has two layers (both end in the same idempotent
    :meth:`failover`): the probe thread marks a replica DOWN after
    ``probe_failure_threshold`` consecutive failed ``/health`` probes
    (the fast path), and a per-replica watchdog heartbeat — age =
    seconds since the last successful probe — backstops it through the
    standard comm-timeout machinery (``heartbeat_timeout_s``).
    """

    def __init__(self, factory: Optional[Callable] = None,
                 replicas: int = 2,
                 journal_root: Optional[str] = None,
                 probe_interval_s: float = 0.25,
                 probe_timeout_s: float = 5.0,
                 probe_failure_threshold: int = 2,
                 heartbeat_timeout_s: float = 10.0,
                 breaker_threshold: int = 3,
                 breaker_reset_s: float = 1.0):
        self.probe_interval_s = float(probe_interval_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self.probe_failure_threshold = max(1, int(probe_failure_threshold))
        self.heartbeat_timeout_s = float(heartbeat_timeout_s)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_reset_s = float(breaker_reset_s)
        self._factory = factory
        self._lock = threading.Lock()
        self.replicas: "OrderedDict[str, Replica]" = OrderedDict()
        self._hb_ids: Dict[str, int] = {}
        self._failed_over: set = set()
        self._migration_listeners: List[Callable] = []
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._spawn_seq = 0     # next factory replica's ordinal
        if factory is not None:
            if journal_root is None:
                import tempfile
                journal_root = tempfile.mkdtemp(prefix="fleet-journal-")
            self.journal_root = journal_root
            for i in range(int(replicas)):
                self.spawn_replica()
        else:
            self.journal_root = journal_root

    # ------------------------------------------------------- membership
    def _register(self, rep: Replica) -> None:
        with self._lock:
            self.replicas[rep.name] = rep
        _replica_up.set(0, replica=rep.name)   # until the first probe
        self._note_size()

    def _note_size(self) -> None:
        with self._lock:
            n = sum(1 for r in self.replicas.values()
                    if r.state != Replica.DEAD)
        _fleet_size_g.set(n)

    def spawn_replica(self) -> Replica:
        """Build ONE more in-process replica from the factory (elastic
        scale-up, ISSUE 19): fresh name, fresh journal directory,
        started on port 0 and registered once its readiness signal
        fires.  With the probe thread running the newcomer gets its
        heartbeat armed and an immediate probe, so the router can route
        to it without waiting out a probe interval."""
        if self._factory is None:
            raise RuntimeError("spawn_replica needs a replica factory")
        import os
        with self._lock:
            name = f"r{self._spawn_seq}"
            self._spawn_seq += 1
        jdir = (None if self.journal_root is None
                else os.path.join(self.journal_root, name))
        srv = self._factory(name, jdir)
        srv.start()
        srv.wait_ready(30.0)
        rep = Replica(name, f"http://{srv.host}:{srv.port}",
                      journal_dir=jdir, server=srv,
                      breaker_threshold=self.breaker_threshold,
                      breaker_reset_s=self.breaker_reset_s)
        self._register(rep)
        if self._probe_thread is not None:
            self._arm_heartbeat(rep)
            self.probe_once(rep)
        return rep

    def retire_replica(self, name: str,
                       timeout_s: float = 30.0) -> bool:
        """Drain-then-retire one replica (elastic scale-down,
        ISSUE 19): flip it DRAINING so the router stops sending new
        work, let every in-flight generation finish, then stop the
        server and deregister.  The state goes DEAD *before* the
        listener drops so a probe racing the teardown can never read
        the dead socket as a failure and trigger a failover — this is
        a deliberate, clean exit, not a death.  Returns False when the
        drain timed out (the replica is retired regardless: in-flight
        work past the timeout is ABANDONED, so size the timeout to the
        workload)."""
        rep = self.replica(name)
        ok = True
        rep.state = Replica.DRAINING
        if rep.server is not None:
            try:
                rep.server.begin_drain()
                ok = bool(rep.server.wait_drained(timeout_s))
            except Exception:  # noqa: BLE001 — retire regardless
                ok = False
        rep.state = Replica.DEAD
        self._disarm_heartbeat(name)
        if rep.server is not None:
            try:
                rep.server.stop()
            except Exception:  # noqa: BLE001 — already going away
                pass
        with self._lock:
            self.replicas.pop(name, None)
            self._failed_over.discard(name)
        _replica_up.set(0, replica=name)
        self._note_size()
        return ok

    def add_replica(self, name: str, url: str,
                    journal_dir: Optional[str] = None,
                    proc=None) -> Replica:
        """Register an external (typically subprocess) replica.  Its
        ``journal_dir`` must be reachable from THIS process for
        journal-backed failover to recover anything."""
        rep = Replica(name, url, journal_dir=journal_dir, proc=proc,
                      breaker_threshold=self.breaker_threshold,
                      breaker_reset_s=self.breaker_reset_s)
        self._register(rep)
        if self._probe_thread is not None:
            self._arm_heartbeat(rep)
        return rep

    def replica(self, name: str) -> Replica:
        with self._lock:
            return self.replicas[name]

    def routable_replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self.replicas.values() if r.routable]

    def all_replicas(self) -> List[Replica]:
        with self._lock:
            return list(self.replicas.values())

    def add_migration_listener(self, fn: Callable) -> None:
        """``fn(request_id, destination_replica_name)`` per migrated
        request — the router re-points its ownership map here."""
        self._migration_listeners.append(fn)

    # ------------------------------------------------------- lifecycle
    def start(self) -> "ReplicaSupervisor":
        from ..distributed.watchdog import CommTaskManager
        with self._lock:
            reps = list(self.replicas.values())
        for rep in reps:
            self._arm_heartbeat(rep)
        CommTaskManager.instance().start()
        self._stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-supervisor",
            daemon=True)
        self._probe_thread.start()
        return self

    def stop(self, stop_replicas: bool = True) -> None:
        """Stop probing and deregister every heartbeat; with
        ``stop_replicas`` the in-process replicas drain-free hard-stop
        too (their own stop paths deregister their engine/journal
        heartbeats — the ISSUE 14 satellite contract)."""
        self._stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        from ..distributed.watchdog import CommTaskManager
        mgr = CommTaskManager.instance()
        with self._lock:
            hbs = list(self._hb_ids.values())
            self._hb_ids.clear()
            reps = list(self.replicas.values())
        for hid in hbs:
            mgr.unregister_heartbeat(hid)
        if stop_replicas:
            for rep in reps:
                if rep.server is not None and rep.state != Replica.DEAD:
                    try:
                        rep.server.stop()
                    except Exception:  # noqa: BLE001 — best effort
                        pass

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _arm_heartbeat(self, rep: Replica) -> None:
        from ..distributed.watchdog import CommTaskManager

        def age() -> Optional[float]:
            if rep.state == Replica.DEAD:
                return None         # failover done; probe re-arms never
            t0 = rep.last_ok if rep.last_ok is not None else rep.created_at
            return time.monotonic() - t0

        hid = CommTaskManager.instance().register_heartbeat(
            f"fleet/{rep.name}", age, self.heartbeat_timeout_s,
            on_timeout=lambda: self._failover_async(rep.name))
        with self._lock:
            self._hb_ids[rep.name] = hid

    def _disarm_heartbeat(self, name: str) -> None:
        from ..distributed.watchdog import CommTaskManager
        with self._lock:
            hid = self._hb_ids.pop(name, None)
        if hid is not None:
            CommTaskManager.instance().unregister_heartbeat(hid)

    # --------------------------------------------------------- probing
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            with self._lock:
                reps = list(self.replicas.values())
            for rep in reps:
                if rep.state == Replica.DEAD:
                    continue
                self.probe_once(rep)

    def probe_once(self, rep: Replica) -> bool:
        """ONE health probe (public so tests drive deterministic
        scans).  Success refreshes the heartbeat and the routing
        inputs (draining flag, Retry-After hint); the
        ``probe_failure_threshold``-th consecutive failure triggers
        failover."""
        try:
            _faults.maybe_fire("replica_probe")
            status, payload, _ = _http_json(
                rep.url + "/health", timeout=self.probe_timeout_s)
            if status != 200:
                raise RuntimeError(f"health probe returned {status}")
        except Exception:  # noqa: BLE001 — a probe failure is data
            rep.probe_failures += 1
            if rep.probe_failures >= self.probe_failure_threshold \
                    and rep.state not in (Replica.DOWN, Replica.DEAD):
                rep.state = Replica.DOWN
                _replica_up.set(0, replica=rep.name)
                self._failover_async(rep.name)
            return False
        with self._lock:
            if rep.state == Replica.DEAD \
                    or rep.name in self._failed_over:
                # a probe that raced a concurrent failover must not
                # resurrect the replica: it has been (or is being)
                # fenced and its streams migrated — only restart()
                # re-registers it
                return False
            rep.last_ok = time.monotonic()
            rep.probe_failures = 0
            rep.health = payload
            rep.retry_after_hint = int(payload.get("retry_after_hint",
                                                   1))
            rep.state = (Replica.DRAINING if payload.get("draining")
                         else Replica.UP)
        _replica_up.set(1, replica=rep.name)
        return True

    # -------------------------------------------------------- failover
    def _failover_async(self, name: str) -> None:
        """Run failover off the caller's thread (probe loop or the
        watchdog scan thread must never block on migration HTTP)."""
        with self._lock:
            if name in self._failed_over:
                return
            self._failed_over.add(name)
        threading.Thread(target=self.failover, args=(name,),
                         kwargs={"_pre_claimed": True},
                         name=f"fleet-failover-{name}",
                         daemon=True).start()

    def failover(self, name: str, _pre_claimed: bool = False) -> int:
        """Journal-backed failover (THE tentpole mechanism): declare
        ``name`` dead, recover its write-ahead journal's live set, and
        migrate every entry to surviving replicas through their
        ``restore(strict=False)`` admission path — greedy, sampled,
        prefix-hit and draft streams all resume bit-exactly elsewhere
        (the PR 8/13 replay contract).  Migrated ids are retired in the
        SOURCE journal (``why="migrated"``), so a replica restarted
        over the same directory resumes nothing twice; ids a
        destination rejected as already-live (a crashed earlier
        failover got that far) are retired the same way — the whole
        pass is re-runnable.  Returns the number of migrated requests.
        Idempotent per replica."""
        if not _pre_claimed:
            with self._lock:
                if name in self._failed_over:
                    return 0
                self._failed_over.add(name)
        rep = self.replica(name)
        rep.state = Replica.DEAD
        _replica_up.set(0, replica=name)
        _failovers_total.inc(replica=name)
        self._disarm_heartbeat(name)
        # FENCE before touching the journal (STONITH): a false-positive
        # detection — a replica that was merely GIL-stalled or starved
        # behind slow probes — must not leave a LIVE writer appending
        # to the directory the recovery below compacts and consumes,
        # nor keep serving streams that are about to run elsewhere.
        # kill() is idempotent on a real corpse; with fencing a false
        # positive costs one replica's availability, never correctness
        # (its streams migrate bit-exactly like a true death's).  A
        # URL-only replica with no process/server handle cannot be
        # fenced here — its journal_dir should only be set when the
        # supervisor truly owns the replica's lifecycle.
        try:
            rep.kill()
        except Exception:  # noqa: BLE001 — fence is best-effort
            pass
        migrated = 0
        try:
            migrated = self._migrate_journal(rep)
        except Exception as e:  # noqa: BLE001 — a failover bug must
            # not kill the supervisor; the survivors keep serving
            warnings.warn(f"fleet failover for {name!r} failed: {e!r}")
        _migrated_total.inc(migrated, replica=name)
        return migrated

    def _migrate_journal(self, rep: Replica) -> int:
        import os
        if not rep.journal_dir or not os.path.isdir(rep.journal_dir):
            return 0
        from .journal import RequestJournal
        # recovering CONSTRUCTS the journal over the dead replica's
        # segments: torn tails truncated, live set compacted durable —
        # the same crash-loop-safe scan a relaunched replica would run
        jrnl = RequestJournal(rep.journal_dir, fsync="os")
        try:
            entries = jrnl.recovered_requests()
            if not entries:
                return 0
            migrated = self._place_entries(rep, entries, jrnl)
            jrnl.flush(sync=True, timeout=30.0)
            return migrated
        finally:
            jrnl.close()

    def _place_entries(self, rep: Replica, entries: List[dict],
                       jrnl) -> int:
        """Distribute the recovered live set over routable survivors.
        Entries are grouped by their page-provenance prefix key
        (ISSUE 14 satellite) so sharers of one cached prefix land on
        the SAME destination: the first sharer's replay re-registers
        the prefix there and the rest hit it — the destination's
        prefix index is re-warmed once, not N times."""
        groups: "OrderedDict[str, List[dict]]" = OrderedDict()
        for i, e in enumerate(entries):
            key = (e.get("prefix") or {}).get("key") or f"_solo{i}"
            groups.setdefault(key, []).append(e)
        migrated = 0
        gi = 0
        for key, group in groups.items():
            placed, duplicates = self._place_group(group, start=gi)
            gi += 1
            for rid, dest in placed.items():
                jrnl.append_retire(rid, why="migrated")
                for fn in self._migration_listeners:
                    try:
                        fn(rid, dest)
                    except Exception:  # noqa: BLE001 — listener bug
                        pass
            for rid, dest in duplicates.items():
                # the destination already knew the id (a router retry
                # landed it there first, or an earlier crashed
                # failover did): retire it in the source journal so a
                # restarted replica cannot resurrect the duplicate
                jrnl.append_retire(rid, why="duplicate")
                for fn in self._migration_listeners:
                    try:
                        fn(rid, dest)
                    except Exception:  # noqa: BLE001
                        pass
            migrated += len(placed)
            lost = [e.get("request_id") for e in group
                    if e.get("request_id") not in placed
                    and e.get("request_id") not in duplicates]
            if lost:
                warnings.warn(
                    f"fleet failover for {rep.name!r} could not place "
                    f"{lost} on any survivor; their journal entries "
                    "remain for a future restart of the replica")
        return migrated

    def _place_group(self, group: List[dict], start: int = 0):
        """POST one prefix-group to survivors until every entry lands
        (or every survivor refused).  Returns ``(placed, duplicates)``
        — request_id -> destination name for entries the destination
        restored, and for ids it already KNEW (the dedup outcome: a
        router retry landed them there first, or an earlier crashed
        failover did — re-run safety either way)."""
        placed: Dict[str, str] = {}
        duplicates: Dict[str, str] = {}
        pending = list(group)
        survivors = self.routable_replicas()
        if not survivors:
            return placed, duplicates
        for k in range(len(survivors)):
            dest = survivors[(start + k) % len(survivors)]
            try:
                status, payload, _ = _http_json(
                    dest.url + "/admin/migrate",
                    body={"requests": pending},
                    timeout=max(60.0, self.probe_timeout_s))
            except Exception:  # noqa: BLE001 — survivor went away too
                continue
            if status != 200:
                continue
            for w in payload.get("warnings", ()):
                warnings.warn(f"fleet migration to {dest.name!r}: {w}")
            for rid in payload.get("restored", ()):
                placed[rid] = dest.name
            for rid in payload.get("live", ()):
                duplicates[rid] = dest.name
            done = set(placed) | set(duplicates)
            pending = [e for e in pending
                       if e.get("request_id") not in done]
            if not pending:
                break
        return placed, duplicates

    # ------------------------------------------------------------ misc
    def kill(self, name: str) -> None:
        """Hard-kill a replica (test/chaos hook) — the supervisor does
        NOT react here; the probe/heartbeat machinery must detect the
        death exactly as it would a real one."""
        self.replica(name).kill()

    def restart(self, name: str) -> Replica:
        """Replace a DEAD in-process replica with a fresh one from the
        factory over the same journal directory (post-failover the
        directory's live set is empty — migrated ids were retired — so
        the newcomer resumes nothing).  The old heartbeat was
        deregistered at failover; the replacement gets its own."""
        if self._factory is None:
            raise RuntimeError("restart needs a replica factory")
        old = self.replica(name)
        if old.state != Replica.DEAD:
            raise RuntimeError(f"replica {name!r} is {old.state}, "
                               "not dead; failover first")
        srv = self._factory(name, old.journal_dir)
        srv.start()
        srv.wait_ready(30.0)
        rep = Replica(name, f"http://{srv.host}:{srv.port}",
                      journal_dir=old.journal_dir, server=srv,
                      breaker_threshold=self.breaker_threshold,
                      breaker_reset_s=self.breaker_reset_s)
        with self._lock:
            self.replicas[name] = rep
            self._failed_over.discard(name)
        if self._probe_thread is not None:
            self._arm_heartbeat(rep)
        return rep

    def info(self) -> dict:
        """JSON-able fleet state for the router's ``/health``."""
        with self._lock:
            reps = list(self.replicas.values())
        return {
            "replicas": {
                r.name: {
                    "url": r.url,
                    "state": r.state,
                    "circuit": r.breaker.state,
                    "retry_after_hint": r.retry_after_hint,
                    "journal_dir": r.journal_dir,
                } for r in reps},
            "routable": sum(1 for r in reps if r.routable),
            "size": len(reps),
        }


class FleetRouter(_ServerLifecycle):
    """HTTP front for a supervised fleet (see the module docstring for
    the robustness kit).  ``POST /generate`` bodies are the
    GenerationServer contract verbatim; the router pins a
    ``request_id`` when the client did not, so every admission is
    idempotent and every reply carries the ``/result/<id>`` handles."""

    def __init__(self, supervisor: ReplicaSupervisor,
                 host: str = "127.0.0.1", port: int = 0,
                 access_log: bool = False,
                 admit_attempts: int = 6,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 1.0,
                 forward_timeout_s: float = 600.0,
                 attach_timeout_s: float = 120.0,
                 result_poll_s: float = 0.05,
                 owner_map_size: int = 4096,
                 seed: int = 0):
        self.supervisor = supervisor
        supervisor.add_migration_listener(self._note_migrated)
        self.admit_attempts = max(1, int(admit_attempts))
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.forward_timeout_s = float(forward_timeout_s)
        self.attach_timeout_s = float(attach_timeout_s)
        self.result_poll_s = float(result_poll_s)
        self._rng = random.Random(seed)     # backoff jitter (seeded)
        self._rr = 0                        # round-robin cursor
        self._owners_lock = threading.Lock()
        self._owner_map_size = int(owner_map_size)
        self._owners: "OrderedDict[str, str]" = OrderedDict()
        self._init_stats(access_log)
        outer = self

        class Handler(_JsonHandler):
            server_kind = "fleet"
            _outer = outer

            def do_GET(self):
                if self.path == "/health":
                    with self._track("/health"):
                        self._reply(200, outer.fleet_health())
                elif self.path == "/metrics":
                    with self._track("/metrics"):
                        self._reply_text(200, monitor.prometheus_text())
                elif self.path.startswith("/result/"):
                    with self._track("/result"):
                        rid = self.path[len("/result/"):]
                        hit = outer.lookup_result(rid)
                        if hit is None:
                            self._reply(404, {
                                "error": f"unknown request id {rid!r} "
                                         "on every replica"})
                        else:
                            code = (202 if hit.get("status") == "pending"
                                    else 200)
                            self._reply(code, hit)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/generate":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                with self._track("/generate"):
                    try:
                        body = self._read_json()
                        if not isinstance(body, dict) \
                                or "input_ids" not in body:
                            raise ValueError(
                                "request body must be a JSON object "
                                "with input_ids")
                    except (ValueError, json.JSONDecodeError) as e:
                        self._reply(400, {"error": str(e)})
                        return
                    code, payload, headers = outer.route_generate(body)
                    self._reply(code, payload, headers=headers or None)

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # --------------------------------------------------------- helpers
    def _note_migrated(self, rid: str, dest: str) -> None:
        self._claim_owner(rid, dest)

    def _claim_owner(self, rid: str, name: str) -> None:
        with self._owners_lock:
            self._owners[rid] = name
            self._owners.move_to_end(rid)
            while len(self._owners) > self._owner_map_size:
                self._owners.popitem(last=False)

    def _owner_of(self, rid: str) -> Optional[str]:
        with self._owners_lock:
            return self._owners.get(rid)

    @staticmethod
    def row_ids(request_id: str, rows: int) -> List[str]:
        """The engine's per-row id convention for a multi-row body."""
        if rows == 1:
            return [request_id]
        return [f"{request_id}/{i}" for i in range(rows)]

    def _candidates(self, prefer: Optional[str] = None
                    ) -> List[Replica]:
        """Routable replicas in round-robin order (the cursor advances
        per call, so consecutive admissions spread).  ``prefer`` moves
        that replica to the front — the retry-dedup path forwards a
        pinned id to its recorded owner FIRST, so the far engine's
        already-live rejection can catch a duplicate."""
        reps = self.supervisor.routable_replicas()
        if not reps:
            return []
        with self._owners_lock:
            self._rr += 1
            k = self._rr
        out = [reps[(k + i) % len(reps)] for i in range(len(reps))]
        if prefer is not None:
            out.sort(key=lambda r: r.name != prefer)
        return out

    # --------------------------------------------------------- routing
    def fleet_health(self) -> dict:
        info = self.supervisor.info()
        info.update({
            "status": "ok" if info["routable"] else "unavailable",
            "uptime_s": round(self.uptime_s, 3),
            "requests_total": self.requests_served,
        })
        return info

    def lookup_result(self, rid: str) -> Optional[dict]:
        """``/result/<rid>`` across the fleet: the owning replica
        first, then every live replica (ownership can be stale right
        after a migration the listener has not delivered yet)."""
        order: List[Replica] = []
        owner = self._owner_of(rid)
        for r in self.supervisor.all_replicas():
            if r.name == owner:
                order.insert(0, r)
            elif r.state not in (Replica.DOWN, Replica.DEAD):
                order.append(r)
        for r in order:
            try:
                status, payload, _ = _http_json(
                    r.url + f"/result/{rid}", timeout=10.0)
            except Exception:  # noqa: BLE001 — replica unreachable
                continue
            if status in (200, 202):
                self._claim_owner(rid, r.name)
                payload["replica"] = r.name
                return payload
        return None

    def route_generate(self, body: dict):
        """The admission path: returns ``(status, payload, headers)``.

        Bounded retry with exponential backoff + jitter; idempotent by
        the pinned ``request_id`` — a retried admit that actually
        landed is detected by the far engine's already-live rejection
        (or by finding the id on a replica) and RE-ATTACHED through the
        result surface instead of re-executed.  A replica that dies
        mid-forward is survived the same way: the router waits for
        journal-backed failover to land the id on a survivor and
        returns the completed stream as if nothing happened."""
        body = dict(body)
        rid = body.get("request_id")
        if rid is None:
            rid = f"fleet-{uuid.uuid4().hex[:16]}"
            body["request_id"] = rid
        rid = str(rid)
        try:
            rows = len(body["input_ids"])
            prompt_len = max(len(r) for r in body["input_ids"])
        except (TypeError, ValueError):
            return 400, {"error": "input_ids must be 2-D"}, {}
        row_ids = self.row_ids(rid, rows)
        eos = body.get("eos_token_id")

        # retry dedup, fleet-wide: a client-pinned id the router has
        # ALREADY routed may still be live — attaching beats admitting
        # a second copy onto a different replica (the per-replica
        # already-live rejection can only catch same-replica retries).
        # A finished id falls through to normal admission: deliberate
        # id reuse after completion keeps the engine's resubmit
        # semantics.
        owner = self._owner_of(row_ids[0])
        if owner is not None:
            hit = self.lookup_result(row_ids[0])
            if hit is not None and hit.get("status") == "pending":
                attached = self._attach(row_ids, prompt_len, eos)
                if attached is not None:
                    return attached

        saturated_hints: List[int] = []
        for attempt in range(self.admit_attempts):
            saturated_hints = []
            hard_failures = 0
            routed = False
            for rep in self._candidates(prefer=owner):
                if not rep.breaker.allow():
                    continue
                routed = True
                try:
                    _faults.maybe_fire("route_admit")
                    # claim ownership BEFORE the (long, blocking)
                    # forward: a concurrent retry of the same pinned
                    # id must find the owner and take the attach path
                    # — claiming only after completion leaves a
                    # generation-wide window where the retry would
                    # admit a second copy on another replica.  A claim
                    # gone stale (this attempt fails) is harmless:
                    # lookup falls back to the fleet-wide scan and the
                    # next landing attempt re-claims.
                    for rr in row_ids:
                        self._claim_owner(rr, rep.name)
                    status, payload, headers = _http_json(
                        rep.url + "/generate", body=body,
                        timeout=self.forward_timeout_s)
                except _faults.FaultError:
                    # injected route failure (testing): before any
                    # replica saw the request — plain retry
                    _router_retries.inc(replica=rep.name)
                    rep.breaker.record_failure()
                    hard_failures += 1
                    continue
                except ConnectionRefusedError:
                    # nothing listening: the admit DEFINITELY did not
                    # land — free to retry elsewhere immediately
                    _router_retries.inc(replica=rep.name)
                    rep.breaker.record_failure()
                    hard_failures += 1
                    continue
                except Exception:  # noqa: BLE001 — transport died
                    # MID-FORWARD: the request may have been admitted
                    # (and journaled) before the replica died.  The id
                    # is the dedup key: if it surfaces anywhere —
                    # including on a survivor after journal-backed
                    # failover migrates it — attach to THAT stream
                    # rather than running the request twice.
                    _router_retries.inc(replica=rep.name)
                    rep.breaker.record_failure()
                    hard_failures += 1
                    attached = self._attach(row_ids, prompt_len, eos,
                                            require_presence=True)
                    if attached is not None:
                        return attached
                    continue
                if status == 200:
                    rep.breaker.record_success()
                    for rr in row_ids:
                        self._claim_owner(rr, rep.name)
                    return 200, payload, {}
                if status == 429:
                    # saturated, not sick: no breaker penalty — collect
                    # the class-aware hint for fleet aggregation
                    try:
                        saturated_hints.append(int(
                            headers.get("Retry-After", 1)))
                    except (TypeError, ValueError):
                        saturated_hints.append(1)
                    continue
                if status == 503:
                    if "engine stopped" in str(payload.get("error", "")):
                        # the replica DIED under this forward (its
                        # in-flight handler errored out during engine
                        # teardown — the in-process kill emulation
                        # surfaces death as this 503 before the
                        # listener drops): same recovery as a dropped
                        # transport.  The admit may be journaled on
                        # the corpse, so wait for failover to land it
                        # on a survivor before considering re-admission
                        # — re-running a journaled stream is the
                        # double-execution the id exists to prevent.
                        _router_retries.inc(replica=rep.name)
                        rep.breaker.record_failure()
                        hard_failures += 1
                        attached = self._attach(row_ids, prompt_len,
                                                eos,
                                                require_presence=True)
                        if attached is not None:
                            return attached
                        continue
                    # draining (or pool-exhausted): route elsewhere;
                    # the next probe refreshes the state gate
                    if payload.get("draining"):
                        rep.state = Replica.DRAINING
                    continue
                if status == 400 and "already live" in str(
                        payload.get("error", "")):
                    # retry dedup (ISSUE 14 tentpole): an earlier
                    # attempt landed here — re-attach, never re-run
                    rep.breaker.record_success()
                    attached = self._attach(row_ids, prompt_len, eos)
                    if attached is not None:
                        return attached
                    return 500, {"error": "request is live on "
                                 f"{rep.name} but unreachable"}, {}
                if 400 <= status < 500:
                    # the CLIENT's request is wrong everywhere —
                    # propagate, never retry
                    rep.breaker.record_success()
                    return status, payload, {}
                # 5xx: replica fault
                _router_retries.inc(replica=rep.name)
                rep.breaker.record_failure()
                hard_failures += 1
            if saturated_hints and not hard_failures:
                # every routable replica said 429: the fleet is FULL,
                # not broken — aggregate min Retry-After and stop
                # burning attempts
                return 429, {"error": "fleet saturated"}, {
                    "Retry-After": str(min(saturated_hints))}
            if not routed and attempt + 1 >= min(2, self.admit_attempts):
                break           # nothing routable twice: fail fast
            if attempt + 1 < self.admit_attempts:
                pause = min(self.backoff_max_s,
                            self.backoff_base_s * (2 ** attempt))
                pause += self._rng.uniform(0, self.backoff_base_s)
                time.sleep(pause)
        if saturated_hints:
            return 429, {"error": "fleet saturated"}, {
                "Retry-After": str(min(saturated_hints))}
        return 503, {"error": "no healthy replica accepted the "
                     "request", "draining": False}, {}

    def _attach(self, row_ids: List[str], prompt_len: int, eos,
                require_presence: bool = False):
        """Re-attach to an already-admitted generation through the
        result surface: poll every row id until done, then assemble the
        GenerationServer /generate reply shape.  With
        ``require_presence``, give up early (return None) if no replica
        has EVER seen the ids — the caller may then safely re-admit
        (the transport died before admission).  Presence is granted a
        failover-sized grace window: an id journaled on a corpse is
        invisible until migration lands it on a survivor."""
        deadline = time.monotonic() + self.attach_timeout_s
        presence_deadline = time.monotonic() + max(
            5.0, 4 * self.supervisor.heartbeat_timeout_s)
        seen = False
        outs: Dict[str, List[int]] = {}
        while time.monotonic() < deadline:
            pending = False
            for rr in row_ids:
                if rr in outs:
                    continue
                hit = self.lookup_result(rr)
                if hit is None:
                    pending = True
                    continue
                seen = True
                if hit.get("status") == "done":
                    outs[rr] = [int(t) for t in hit["output_ids"]]
                elif hit.get("status") == "error":
                    return 500, {"error": hit.get("error", "request "
                                 "failed"), "request_ids": row_ids}, {}
                else:
                    pending = True
            if not pending:
                break
            if require_presence and not seen \
                    and time.monotonic() > presence_deadline:
                return None
            time.sleep(self.result_poll_s)
        if len(outs) != len(row_ids):
            return 504, {"error": "re-attach timed out with rows still "
                         "pending", "request_ids": row_ids}, {}
        width = max(len(v) for v in outs.values())
        pad = 0 if eos is None else int(eos)
        output = [outs[rr] + [pad] * (width - len(outs[rr]))
                  for rr in row_ids]
        return 200, {"output_ids": output,
                     "new_tokens": width - prompt_len,
                     "request_ids": row_ids,
                     "reattached": True}, {}


class FleetAutoscaler:
    """Elastic replica count (ISSUE 19 tentpole d): close the loop
    from the telemetry the supervisor already scrapes — per-replica
    ``/health`` queue depth, Retry-After hints and the engine's
    brownout rung — to the replica count, within ``[min_replicas,
    max_replicas]``.

    The control law is deliberately boring: mean routable-replica
    queue depth at or above ``scale_up_depth`` (or any replica browned
    out) for ``up_patience`` consecutive evaluations spawns ONE
    replica; mean depth at or below ``scale_down_depth`` with every
    ladder at rung 0 for ``down_patience`` evaluations drain-then-
    retires the NEWEST routable replica (the oldest replicas hold the
    warmest prefix caches).  Asymmetric patience plus ``cooldown_s``
    between any two actions is the hysteresis: scale-up is eager
    (overload is now), scale-down is reluctant (a flapping workload
    must not thrash replica churn), and one action per cooldown bounds
    the rate either way.

    ``evaluate()`` is public so tests and bench lanes can drive the
    loop deterministically; ``start()`` runs it on a thread every
    ``interval_s`` against fresh probe data."""

    def __init__(self, supervisor: ReplicaSupervisor,
                 min_replicas: int = 1, max_replicas: int = 4,
                 scale_up_depth: float = 8.0,
                 scale_down_depth: float = 0.5,
                 interval_s: float = 0.25,
                 up_patience: int = 2, down_patience: int = 8,
                 cooldown_s: float = 2.0,
                 drain_timeout_s: float = 30.0):
        if int(min_replicas) < 1 or int(max_replicas) < int(min_replicas):
            raise ValueError(
                "need 1 <= min_replicas <= max_replicas, got "
                f"[{min_replicas}, {max_replicas}]")
        self.supervisor = supervisor
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.scale_up_depth = float(scale_up_depth)
        self.scale_down_depth = float(scale_down_depth)
        self.interval_s = float(interval_s)
        self.up_patience = max(1, int(up_patience))
        self.down_patience = max(1, int(down_patience))
        self.cooldown_s = float(cooldown_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._up_streak = 0
        self._down_streak = 0
        self._last_scale = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------- lifecycle
    def start(self) -> "FleetAutoscaler":
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-autoscaler", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            # a drain-in-progress holds the loop; the drain timeout
            # bounds it
            self._thread.join(timeout=self.drain_timeout_s + 10.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception as e:  # noqa: BLE001 — the autoscaler
                # must never take the fleet down with it; a failed
                # spawn (OOM, port exhaustion) retries next evaluation
                warnings.warn(f"fleet autoscaler evaluation failed: "
                              f"{e!r}")

    # --------------------------------------------------------- control
    def pressure(self) -> dict:
        """The loop's current inputs (also handy for bench output)."""
        reps = self.supervisor.routable_replicas()
        depth = 0
        brownout = 0
        hint = 0
        for r in reps:
            h = r.health or {}
            depth += int(h.get("queued_sequences", 0) or 0)
            sched = h.get("scheduler") or {}
            brownout = max(brownout,
                           int(sched.get("brownout_level", 0) or 0))
            hint = max(hint, int(r.retry_after_hint or 0))
        return {
            "routable": len(reps),
            "mean_depth": depth / max(1, len(reps)),
            "max_brownout": brownout,
            "max_retry_after": hint,
        }

    def evaluate(self) -> Optional[str]:
        """One control-loop step: returns ``"up"``/``"down"`` when it
        scaled, else None."""
        reps = self.supervisor.routable_replicas()
        if not reps:
            # nothing routable means a failover is in flight — that is
            # the supervisor's emergency, not a capacity signal
            self._up_streak = self._down_streak = 0
            return None
        size = sum(1 for r in self.supervisor.all_replicas()
                   if r.state != Replica.DEAD)
        p = self.pressure()
        overloaded = (p["mean_depth"] >= self.scale_up_depth
                      or p["max_brownout"] >= 1)
        calm = (p["mean_depth"] <= self.scale_down_depth
                and p["max_brownout"] == 0)
        now = time.monotonic()
        cooled = now - self._last_scale >= self.cooldown_s
        if overloaded:
            self._down_streak = 0
            self._up_streak += 1
            if self._up_streak >= self.up_patience \
                    and size < self.max_replicas and cooled:
                self._up_streak = 0
                rep = self.supervisor.spawn_replica()
                self._last_scale = time.monotonic()
                self.scale_ups += 1
                _scale_events.inc(direction="up")
                warnings.warn(
                    f"fleet scaled UP to {size + 1} replicas "
                    f"({rep.name}): mean queue depth "
                    f"{p['mean_depth']:.1f}, brownout "
                    f"{p['max_brownout']}")
                return "up"
        elif calm:
            self._up_streak = 0
            self._down_streak += 1
            if self._down_streak >= self.down_patience \
                    and size > self.min_replicas and cooled:
                self._down_streak = 0
                victim = max(reps, key=lambda r: r.created_at)
                self.supervisor.retire_replica(
                    victim.name, timeout_s=self.drain_timeout_s)
                self._last_scale = time.monotonic()
                self.scale_downs += 1
                _scale_events.inc(direction="down")
                return "down"
        else:
            self._up_streak = self._down_streak = 0
        return None

    def info(self) -> dict:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "scale_up_depth": self.scale_up_depth,
            "scale_down_depth": self.scale_down_depth,
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            **self.pressure(),
        }
