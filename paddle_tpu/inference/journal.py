"""Write-ahead request journal: SIGKILL-grade crash recovery (ISSUE 13).

PR 8's snapshot/restore is crash-consistent only for failures the
process gets to see: SIGTERM snapshots-then-drains, but a SIGKILL,
OOM-kill or power loss destroys every in-flight request.  Because the
replay primitive is already bit-exact for greedy AND sampled rows (the
fused sampler's counter is ``(seed, absolute position)``), durable
recovery reduces to durably logging tiny HOST-side state — prompt,
seed, generated ids, the pending next token — never KV.

:class:`RequestJournal` is that log:

  * **append-only, CRC32-framed records** — a 2-byte magic, the payload
    length, the payload's CRC32, then the JSON payload.  Three record
    types: ``admit`` (the full request state at admission — a restored
    request's record carries its generated tokens, which makes replay
    idempotent by request_id), ``step`` (ONE coalesced record per
    engine iteration: the ids admitted to a slot plus, per surviving
    row, the tokens appended and the new pending ``next_token``),
    ``retire`` (done/cancel/expire/quarantine/fault — the live set is
    admitted minus retired) and ``pages`` (ISSUE 14 satellite —
    **page provenance**: which prefix-cache pages a request acquired at
    admission or registered at prefill completion, with the stable
    content hash of the shared prefix; the fleet's journal-backed
    failover groups migrating requests by that key so sharers land on
    one destination replica and re-warm its prefix index once, and a
    disaggregated decode tier — the ROADMAP slice this record exists
    for — can re-attach transported pages after a crash);
  * **a dedicated writer thread** — every engine/record producer only
    appends to an in-memory queue (one lock, no I/O), so journaling
    never rides the ``_cond`` hot path; the writer serializes, frames,
    writes and fsyncs in batches;
  * **configurable fsync policy** — ``"always"`` (fsync after every
    batch), ``"interval_ms"`` (fsync at most every
    ``fsync_interval_ms``), ``"os"`` (never; the OS page cache decides)
    — plus a watchdog-driven DEGRADED mode: with
    ``fsync_timeout_s`` set, a hung fsync fires the comm watchdog's
    timeout machinery (``comm_timeouts_total``) and flips the journal
    to ``os`` policy (``journal_degraded`` gauge) instead of stalling
    the writer (and, transitively, SIGTERM flushes) forever;
  * **segment rotation + live-set compaction** — segments rotate at
    ``segment_bytes``; once the dead-record ratio (units referencing
    retired requests over total units) crosses
    ``compact_dead_ratio``, the writer rewrites the live set into a
    fresh segment and renames the replaced segments to
    ``*.consumed`` (one generation kept for forensics) —
    ``journal_compactions_total``;
  * **torn-tail tolerance** — recovery truncates each segment at the
    first bad frame (short header, bad magic, bad CRC, short payload),
    counts it (``journal_torn_records_total``) and keeps going: every
    fully-framed record still recovers;
  * **crash-loop-safe recovery** — opening a journal over existing
    segments replays them oldest-first into the live set, then
    performs a RECOVERY COMPACTION (live set written to a fresh
    fsynced segment BEFORE the old segments are renamed consumed), so
    a restart that dies mid-recovery — or mid-compaction, leaving
    both old and compacted segments behind — replays to the SAME live
    set next time: ``admit`` replaces by request_id, ``step``/
    ``retire`` records for unknown ids are ignored.

The SIGTERM snapshot collapses onto this format: with a journal
configured the server's preemption path is just ``flush(sync=True)``
(the crash floor — the WAL already holds everything) plus a final
:meth:`compact` once the drain completes, one persistence format
instead of two.

:func:`durable_replace` / :func:`fsync_file_and_dir` are the shared
atomic-persistence helpers: the historical ``save_snapshot`` tmp+rename
never fsync'd the file or the parent directory, so the rename itself
could be lost on power failure — the journal's segment switch and the
legacy snapshot path now both go through the same fsync discipline.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import time
import warnings
import weakref
import zlib
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from .. import monitor
from ..testing import faults as _faults

__all__ = [
    "RequestJournal", "FSYNC_POLICIES",
    "durable_replace", "fsync_file_and_dir",
]

FSYNC_POLICIES = ("always", "interval_ms", "os")

# ----------------------------------------------------------------------
# co-location registry (ISSUE 19 satellite, ROADMAP item (f)): N
# engines in one process mean N journal writer threads sharing the
# GIL — each waking at the CONFIGURED interval they steal N x the
# GIL share one writer does (PR 14 measured the decode step p50 at
# 4.2 ms solo vs 6.3 ms with two colocated journaling engines).  Every
# engine registers here on start/stop; every live journal scales its
# EFFECTIVE flush cadence by the live-engine count, so the per-host
# writer wake rate stays roughly constant as replicas pack in.
_coloc_lock = threading.Lock()
_live_engines = 0
_journals: "weakref.WeakSet" = weakref.WeakSet()


def live_engines() -> int:
    with _coloc_lock:
        return _live_engines


def _set_live_engines(delta: int) -> int:
    global _live_engines
    with _coloc_lock:
        _live_engines = max(0, _live_engines + delta)
        n = _live_engines
        journals = list(_journals)
    for j in journals:
        j._set_colocation(max(1, n))
    return n


def engine_started() -> int:
    """One more engine is live in this process; returns the new count.
    Called by the engine constructor (any engine, journaled or not —
    a journal-less engine still steps on the same GIL)."""
    return _set_live_engines(+1)


def engine_stopped() -> int:
    return _set_live_engines(-1)

#: frame = MAGIC + <u32 payload length> + <u32 payload crc32> + payload
_MAGIC = b"RJ"
_HEADER = struct.Struct("<II")
_HEADER_LEN = len(_MAGIC) + _HEADER.size

_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".seg"
_CONSUMED_SUFFIX = ".consumed"

# journal telemetry (ISSUE 13): materialized at import so the series
# exist (value 0) the moment any journal-aware process scrapes /metrics
_records_total = monitor.counter(
    "journal_records_total", "records appended to the write-ahead "
    "request journal (admit + coalesced step + retire)")
_bytes_total = monitor.counter(
    "journal_bytes", "framed bytes appended to the write-ahead request "
    "journal")
_fsync_s = monitor.histogram(
    "journal_fsync_seconds", "one journal fsync (the durability cost "
    "of the configured policy)")
_compactions_total = monitor.counter(
    "journal_compactions_total", "live-set compactions (dead-record "
    "ratio crossings, recovery compactions and explicit compact() "
    "calls)")
_torn_total = monitor.counter(
    "journal_torn_records_total", "torn/corrupt frames recovery "
    "truncated at (one per damaged segment tail)")
_recovered_total = monitor.counter(
    "journal_recovered_requests_total", "live requests reconstructed "
    "from journal segments at process restart")
_degraded_g = monitor.gauge(
    "journal_degraded", "1 after a hung/failed fsync flipped the "
    "journal to os-policy degraded mode, else 0")
_records_total.inc(0)
_bytes_total.inc(0)
_compactions_total.inc(0)
_torn_total.inc(0)
_recovered_total.inc(0)
_degraded_g.set(0)


# ------------------------------------------------------------------ fsync
def fsync_file_and_dir(path: str) -> None:
    """fsync ``path`` and its parent directory: the two syncs an
    atomic tmp+rename needs for the RENAME itself to survive power
    loss (the file's data, then the directory entry pointing at it)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def _fsync_dir(dirpath: str) -> None:
    try:
        dfd = os.open(dirpath, os.O_RDONLY)
    except OSError:
        return                      # platform without dir-open semantics
    try:
        os.fsync(dfd)
    except OSError:
        pass                        # directories aren't fsync-able here
    finally:
        os.close(dfd)


def durable_replace(tmp: str, dst: str) -> None:
    """``os.replace`` that survives power failure: fsync the tmp file's
    DATA first (or the rename could publish an empty file), rename,
    then fsync the parent directory so the new entry is durable.  The
    journal's segment switch and ``GenerationServer.save_snapshot``
    share this helper."""
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dst)
    _fsync_dir(os.path.dirname(os.path.abspath(dst)))


# ------------------------------------------------------------- encoding
def _json_default(obj):
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, np.integer):
        return int(obj)
    if isinstance(obj, np.floating):
        return float(obj)
    raise TypeError(f"journal cannot encode {type(obj).__name__}")


def _encode(rec: dict) -> bytes:
    return json.dumps(rec, separators=(",", ":"),
                      default=_json_default).encode()


def _frame(payload: bytes) -> bytes:
    return (_MAGIC + _HEADER.pack(len(payload), zlib.crc32(payload))
            + payload)


def _read_frames(raw: bytes):
    """Yield decoded records from one segment's bytes; a final ``None``
    marks a torn/corrupt frame (short header, bad magic, short or
    corrupt payload) — everything after it is unreadable by
    construction, so the caller truncates there."""
    off, n = 0, len(raw)
    while off < n:
        if off + _HEADER_LEN > n or raw[off:off + 2] != _MAGIC:
            yield None              # torn marker
            return
        length, crc = _HEADER.unpack_from(raw, off + 2)
        start = off + _HEADER_LEN
        end = start + length
        if end > n:
            yield None
            return
        payload = raw[start:end]
        if zlib.crc32(payload) != crc:
            yield None
            return
        try:
            yield json.loads(payload)
        except ValueError:
            yield None
            return
        off = end


# ------------------------------------------------------------- live set
class _LiveSet:
    """The journal's replay state: request_id -> entry dict, plus the
    unit accounting the compaction trigger reads.  Shared by the
    recovery scan and the writer's live mirror so the two can never
    apply records differently.

    Units: an ``admit`` is 1, a ``step`` record is one per admitted id
    + one per row, a ``retire`` is one per id.  ``dead_ratio`` is the
    fraction of units referencing requests no longer live."""

    def __init__(self):
        self.entries: "OrderedDict[str, dict]" = OrderedDict()
        self._units: Dict[str, int] = {}    # live rid -> units held
        self.total_units = 0
        self.live_units = 0

    def apply(self, rec: dict) -> None:
        t = rec.get("t")
        if t == "admit":
            e = rec.get("req") or {}
            rid = e.get("request_id")
            if rid is None:
                return
            if rid in self.entries:     # re-admit replaces (idempotence)
                self.live_units -= self._units.pop(rid)
            self.entries[rid] = dict(e)
            self._units[rid] = 1
            self.total_units += 1
            self.live_units += 1
        elif t == "step":
            for rid in rec.get("adm", ()):
                self.total_units += 1
                e = self.entries.get(rid)
                if e is None:
                    continue
                e["admitted"] = True
                self._units[rid] += 1
                self.live_units += 1
            for row in rec.get("rows", ()):
                rid, toks, nxt = row[0], row[1], row[2]
                self.total_units += 1
                e = self.entries.get(rid)
                if e is None:
                    continue            # compacted-away or retired id
                if toks:
                    e["generated"] = list(e.get("generated") or ()) \
                        + [int(tk) for tk in toks]
                e["next_token"] = None if nxt is None else int(nxt)
                e["admitted"] = True    # emission implies admission
                self._units[rid] += 1
                self.live_units += 1
        elif t == "pages":
            # page provenance (ISSUE 14 satellite): the latest record
            # wins — a request acquires at most one cached prefix and
            # registration supersedes it with the full picture
            rid = rec.get("id")
            self.total_units += 1
            e = self.entries.get(rid)
            if e is None:
                return              # retired/compacted-away id
            e["prefix"] = {
                "event": rec.get("event"),
                "tokens": int(rec.get("tokens") or 0),
                "pages": [int(p) for p in rec.get("pages", ())],
                "key": rec.get("key"),
            }
            self._units[rid] += 1
            self.live_units += 1
        elif t == "retire":
            for rid in rec.get("ids", ()):
                self.total_units += 1
                if rid in self.entries:
                    del self.entries[rid]
                    self.live_units -= self._units.pop(rid)

    @property
    def dead_ratio(self) -> float:
        if self.total_units <= 0:
            return 0.0
        return 1.0 - self.live_units / self.total_units

    def reset_accounting(self) -> None:
        """After a compaction the log holds exactly one admit per live
        entry."""
        self._units = {rid: 1 for rid in self.entries}
        self.total_units = len(self.entries)
        self.live_units = len(self.entries)


class RequestJournal:
    """See the module docstring.  Thread-safe producers
    (:meth:`append_admit` / :meth:`append_step` / :meth:`append_retire`
    only enqueue); one writer thread owns all file I/O."""

    def __init__(self, path: str, fsync: str = "interval_ms",
                 fsync_interval_ms: float = 50.0,
                 segment_bytes: int = 1 << 20,
                 compact_dead_ratio: float = 0.6,
                 compact_min_records: int = 64,
                 fsync_timeout_s: Optional[float] = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync must be one of {FSYNC_POLICIES}, got {fsync!r}")
        self.path = os.path.abspath(path)
        self.fsync_policy = fsync           # configured
        self._policy = fsync                # effective (degrade flips it)
        self.fsync_interval_s = float(fsync_interval_ms) / 1000.0
        # co-location scaling (ISSUE 19 satellite): the writer's
        # EFFECTIVE cadence is interval x live engines on this host,
        # kept current by engine_started()/engine_stopped()
        self._colocation = max(1, live_engines())
        _journals.add(self)
        self.segment_bytes = int(segment_bytes)
        self.compact_dead_ratio = float(compact_dead_ratio)
        self.compact_min_records = int(compact_min_records)
        os.makedirs(self.path, exist_ok=True)
        self._degraded = False
        self._lock = threading.Condition()
        self._queue: List[dict] = []
        self._appended = 0          # records enqueued
        self._written = 0           # records written to the segment file
        self._synced = 0            # records covered by the last fsync
        self._force_sync_below = 0  # flush(sync=True) high-water mark
        self._compact_req = 0       # explicit compact() requests
        self._compact_done = 0
        self._closing = False
        self._closed = False
        self._dirty = False         # bytes written since the last fsync
        self._last_sync = time.monotonic()
        # watchdog heartbeat (ISSUE 13 satellite): the age of the
        # writer's in-flight I/O op — a hung fsync is as visible as a
        # hung collective, and on_timeout degrades instead of stalling
        self._op_started: Optional[float] = None
        self._hb_id: Optional[int] = None
        # ---- recovery: replay whatever a predecessor left behind
        self._live = _LiveSet()
        self.torn_records = 0
        self._recovered: List[dict] = []
        segs = self._segments()
        if segs:
            self._recover(segs)
        self._seg_seq = self._next_seq()
        self._seg_path = self._seg_name(self._seg_seq)
        self._f = open(self._seg_path, "ab")
        _fsync_dir(self.path)        # the new segment's dir entry
        self._writer = threading.Thread(target=self._writer_loop,
                                        name="journal-writer", daemon=True)
        self._writer.start()
        if fsync_timeout_s is not None:
            from ..distributed.watchdog import CommTaskManager
            mgr = CommTaskManager.instance()
            self._hb_id = mgr.register_heartbeat(
                "journal/writer", self._op_age, float(fsync_timeout_s),
                on_timeout=self.degrade)
            mgr.start()
        _degraded_g.set(int(self._degraded))

    # ------------------------------------------------------- segments
    def _seg_name(self, seq: int) -> str:
        return os.path.join(self.path,
                            f"{_SEG_PREFIX}{seq:08d}{_SEG_SUFFIX}")

    def _segments(self) -> List[str]:
        out = []
        for name in os.listdir(self.path):
            if name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX):
                out.append(os.path.join(self.path, name))
        return sorted(out)

    def _next_seq(self) -> int:
        segs = self._segments()
        if not segs:
            return 1
        last = os.path.basename(segs[-1])
        return int(last[len(_SEG_PREFIX):-len(_SEG_SUFFIX)]) + 1

    @property
    def segment_count(self) -> int:
        return len(self._segments())

    # ------------------------------------------------------- recovery
    def _recover(self, segs: List[str]) -> None:
        """Replay ``segs`` oldest-first into the live set, then write a
        RECOVERY COMPACTION before consuming them — the order that
        makes a crash at ANY point here re-runnable (see module
        docstring)."""
        for seg in segs:
            with open(seg, "rb") as f:
                raw = f.read()
            for rec in _read_frames(raw):
                if rec is None:
                    self.torn_records += 1
                    _torn_total.inc()
                    break
                self._live.apply(rec)
        now = time.time()
        self._recovered = [self._restore_entry(e, now)
                           for e in self._live.entries.values()]
        # in-flight streams FIRST (the PR 8 restore convention): if the
        # live set saturates the restoring engine's queues, it is
        # never-started queued work that gets dropped
        self._recovered.sort(
            key=lambda e: 0 if (e.get("generated")
                                or e.get("next_token") is not None
                                or e.get("_admitted")) else 1)
        for e in self._recovered:
            e.pop("_admitted", None)
        _recovered_total.inc(len(self._recovered))
        # recovery compaction: live set into a fresh durable segment,
        # THEN rename the replaced segments -> *.consumed
        seq = self._next_seq()
        self._write_compact_segment(seq, consumed=segs)
        self._live.reset_accounting()

    @staticmethod
    def _restore_entry(e: dict, now: float) -> dict:
        """A journal entry in the snapshot-restore format: absolute
        wall-clock deadlines become the remaining-seconds fields the
        ``_restore`` admission branch takes VERBATIM (a journaled None
        means no deadline — never the restoring engine's defaults), and
        an ADMITTED request's (spent) queue-wait deadline is dropped,
        exactly as ``engine.snapshot()`` does."""
        d = dict(e)
        admitted = bool(d.pop("admitted", False))
        ddl = d.pop("deadline_unix", None)
        d["ttl_remaining_s"] = (None if ddl is None
                                else max(1e-3, float(ddl) - now))
        qdl = d.pop("queue_deadline_unix", None)
        d["queue_timeout_remaining_s"] = (
            None if qdl is None or admitted
            else max(1e-3, float(qdl) - now))
        d["_admitted"] = admitted
        return d

    def recovered_requests(self) -> List[dict]:
        """The live set a predecessor's segments held, as
        snapshot-format entries ``engine.restore`` consumes (deadlines
        converted from the journaled absolute wall-clock instants)."""
        return [dict(e) for e in self._recovered]

    # ------------------------------------------------------ producers
    def _append(self, rec: dict) -> None:
        with self._lock:
            if self._closing or self._closed:
                return              # late retire during teardown
            self._queue.append(rec)
            self._appended += 1
            self._lock.notify_all()

    def append_admit(self, entry: dict) -> None:
        """``entry`` is the full request state (snapshot-entry fields
        plus ``deadline_unix``/``queue_deadline_unix``); a restored
        request's entry carries its generated tokens, which is what
        makes replay idempotent by request_id."""
        self._append({"t": "admit", "req": entry})

    def append_step(self, admitted_ids, rows, dispatches=None,
                    mode=None) -> None:
        """ONE coalesced record per engine iteration: ``admitted_ids``
        are requests that took a slot this iteration, ``rows`` is
        ``(request_id, [tokens appended], next_token)`` per surviving
        row (prefill completion is a row with no tokens and the first
        pending sample).

        ``dispatches``/``mode`` (ISSUE 17) describe HOW the iteration
        executed: the number of compiled dispatches it issued and
        ``"ragged"`` (the unified single-dispatch step) vs ``"legacy"``
        (the multi-dispatch composition).  Optional keys — replay
        ignores them (see :class:`_LiveSet`), so journals written
        before the unified step restore unchanged, and journals written
        after it replay on older readers."""
        rec = {
            "t": "step", "adm": [str(i) for i in admitted_ids],
            "rows": [[str(rid), [int(tk) for tk in toks],
                      None if nxt is None else int(nxt)]
                     for rid, toks, nxt in rows]}
        if dispatches is not None:
            rec["n"] = int(dispatches)
        if mode is not None:
            rec["mode"] = str(mode)
        self._append(rec)

    def append_retire(self, request_id: str, why: str = "done") -> None:
        self._append({"t": "retire", "ids": [str(request_id)],
                      "why": why})

    def append_pages(self, request_id: str, event: str, tokens: int,
                     pages, key: Optional[str]) -> None:
        """Page-provenance record (ISSUE 14 satellite): ``event`` is
        ``"acquired"`` (admission mapped a cached prefix read-only) or
        ``"registered"`` (prefill completion retained this prompt's
        page-aligned prefixes), ``tokens`` the page-aligned shared
        length, ``pages`` the replica-local page indices backing it and
        ``key`` the stable content hash of the prefix — the only field
        that means the same thing on a DIFFERENT replica, which is what
        failover grouping and disaggregated re-attach key on."""
        self._append({"t": "pages", "id": str(request_id),
                      "event": str(event), "tokens": int(tokens),
                      "pages": [int(p) for p in pages],
                      "key": key})

    # ------------------------------------------------------- control
    def flush(self, sync: bool = True,
              timeout: Optional[float] = None) -> bool:
        """Block until everything appended so far is written (and, with
        ``sync``, fsynced — forced even under ``os`` policy: this is
        the SIGTERM crash floor).  False if ``timeout`` elapsed."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        with self._lock:
            target = self._appended
            if sync:
                self._force_sync_below = max(self._force_sync_below,
                                             target)
            self._lock.notify_all()
            while (self._written < target
                   or (sync and self._synced < target)):
                if self._closed:
                    return False
                wait = 0.05
                if deadline is not None:
                    wait = min(wait, deadline - time.monotonic())
                    if wait <= 0:
                        return False
                self._lock.wait(wait)
        return True

    def compact(self, wait: bool = True,
                timeout: Optional[float] = None) -> bool:
        """Request a live-set compaction (the SIGTERM post-drain
        refresh: a drained engine compacts to an empty live set, so the
        relaunch resumes nothing)."""
        deadline = (None if timeout is None
                    else time.monotonic() + float(timeout))
        with self._lock:
            if self._closed:
                return False
            self._compact_req += 1
            target = self._compact_req
            self._lock.notify_all()
            if not wait:
                return True
            while self._compact_done < target and not self._closed:
                w = 0.05
                if deadline is not None:
                    w = min(w, deadline - time.monotonic())
                    if w <= 0:
                        return False
                self._lock.wait(w)
        return self._compact_done >= target

    def degrade(self) -> None:
        """Flip to ``os``-policy degraded mode (watchdog ``on_timeout``
        target): admission and SIGTERM flushes must not stall behind a
        hung fsync; durability narrows to what the OS flushes."""
        if self._degraded:
            return
        self._degraded = True
        self._policy = "os"
        _degraded_g.set(1)
        warnings.warn(
            "journal writer fsync exceeded its watchdog timeout; "
            "degrading to fsync='os' (durability now depends on the OS "
            "page cache)")

    def set_policy(self, policy: str) -> None:
        """Explicitly set the EFFECTIVE fsync policy (ISSUE 19: the
        brownout ladder's last rung flips to ``os`` — maximum engine
        throughput, durability narrowed to the OS page cache — and
        de-escalation restores the configured policy by passing
        ``fsync_policy`` back in).  Unlike :meth:`degrade` this is
        reversible and does not mark the journal degraded; while the
        watchdog HAS degraded the journal, the sticky ``os`` policy
        wins and this is a no-op."""
        if policy not in FSYNC_POLICIES:
            raise ValueError(
                f"fsync policy must be one of {FSYNC_POLICIES}, "
                f"got {policy!r}")
        with self._lock:
            if self._degraded:
                return
            if policy == self._policy:
                return
            self._policy = policy
            self._lock.notify_all()

    def _set_colocation(self, n: int) -> None:
        with self._lock:
            self._colocation = max(1, int(n))
            self._lock.notify_all()

    @property
    def effective_fsync_interval_s(self) -> float:
        """The interval the writer actually flushes at: configured
        interval x colocated live engines."""
        return self.fsync_interval_s * self._colocation

    @property
    def degraded(self) -> bool:
        return self._degraded

    @property
    def effective_policy(self) -> str:
        return self._policy

    @property
    def live_count(self) -> int:
        with self._lock:
            return len(self._live.entries)

    def info(self) -> dict:
        """JSON-able state for ``/health``."""
        # listdir OUTSIDE the lock: producers (engine threads holding
        # _cond) block on this lock, and a /health scrape must never
        # put a directory scan on the admission path
        segments = self.segment_count
        with self._lock:
            return {
                "path": self.path,
                "fsync_policy": self.fsync_policy,
                "effective_fsync_policy": self._policy,
                "degraded": self._degraded,
                "colocated_engines": self._colocation,
                "effective_fsync_interval_ms": round(
                    self.effective_fsync_interval_s * 1000.0, 3),
                "segments": segments,
                "live_requests": len(self._live.entries),
                "torn_records": self.torn_records,
            }

    def close(self, timeout: Optional[float] = 10.0) -> None:
        """Drain the queue, final-fsync, stop the writer.  Idempotent.
        Live entries deliberately REMAIN journaled — a close without
        retirement is the crash floor a relaunch resumes from."""
        with self._lock:
            if self._closed and not self._writer.is_alive():
                return
            self._closing = True
            self._lock.notify_all()
        self._writer.join(timeout=timeout)
        with self._lock:
            self._closed = True
            self._lock.notify_all()
        if self._hb_id is not None:
            from ..distributed.watchdog import CommTaskManager
            CommTaskManager.instance().unregister_heartbeat(self._hb_id)
            self._hb_id = None
        try:
            self._f.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # --------------------------------------------------- writer thread
    def _op_age(self) -> Optional[float]:
        t0 = self._op_started
        return None if t0 is None else time.monotonic() - t0

    def _writer_loop(self) -> None:
        while True:
            with self._lock:
                while (not self._queue and not self._closing
                       and self._compact_req <= self._compact_done
                       and not (self._dirty and self._sync_due())):
                    self._lock.wait(min(
                        0.2, max(self.effective_fsync_interval_s, 1e-3)))
                batch = self._queue
                self._queue = []
                closing = self._closing
                want_compact = self._compact_req > self._compact_done
            try:
                if batch:
                    self._write_batch(batch)
                if self._dirty and (closing or self._sync_due()):
                    self._do_fsync()
                if want_compact or self._auto_compact_due():
                    self._compact_io()
                    with self._lock:
                        if want_compact:
                            self._compact_done = self._compact_req
                        self._lock.notify_all()
            except Exception as e:   # noqa: BLE001 — the journal must
                # degrade, never take the serving engine down with it
                warnings.warn(f"journal writer error: {e!r}")
                self.degrade()
                with self._lock:
                    self._written = self._appended
                    self._synced = self._appended
                    if want_compact:
                        self._compact_done = self._compact_req
                    self._lock.notify_all()
            if closing and not self._queue:
                with self._lock:
                    if not self._queue:     # nothing raced in
                        self._lock.notify_all()
                        return

    def _sync_due(self) -> bool:
        if self._synced < self._force_sync_below:
            return True             # a flush(sync=True) is waiting
        if self._policy == "always":
            return True
        if self._policy == "os":
            return False
        return (time.monotonic() - self._last_sync
                >= self.effective_fsync_interval_s)

    def _write_batch(self, batch: List[dict]) -> None:
        for rec in batch:
            payload = _encode(rec)
            frame = _frame(payload)
            self._op_started = time.monotonic()
            torn = False
            try:
                try:
                    _faults.maybe_fire("journal_write")
                except _faults.FaultError:
                    # torn-write emulation: half the frame reaches the
                    # disk (exactly what a crash mid-write leaves), and
                    # the writer ROTATES so later records land in a
                    # fresh segment — recovery truncates the torn tail
                    # and still sees everything written after it
                    self._f.write(frame[:max(4, len(frame) // 2)])
                    self._f.flush()
                    self._dirty = True
                    torn = True
                else:
                    self._f.write(frame)
                    self._dirty = True
            finally:
                self._op_started = None
            with self._lock:
                self._written += 1
                if not torn:
                    # mirror mutated under the lock: live_count/info()
                    # read it from other threads
                    self._live.apply(rec)
            if torn:
                self._rotate()
                continue
            _records_total.inc()
            _bytes_total.inc(len(frame))
            if self._f.tell() > self.segment_bytes:
                self._rotate()       # per record: segments stay bounded
        self._f.flush()
        with self._lock:
            self._lock.notify_all()

    def _do_fsync(self) -> None:
        written = self._written
        self._op_started = time.monotonic()
        t0 = time.perf_counter()
        try:
            try:
                _faults.maybe_fire("journal_fsync")
                os.fsync(self._f.fileno())
            except _faults.FaultError as e:
                warnings.warn(f"journal fsync failed (injected): {e}")
                self.degrade()
            except OSError as e:
                warnings.warn(f"journal fsync failed: {e!r}")
                self.degrade()
        finally:
            self._op_started = None
        _fsync_s.observe(time.perf_counter() - t0)
        self._dirty = False
        self._last_sync = time.monotonic()
        with self._lock:
            self._synced = max(self._synced, written)
            self._lock.notify_all()

    def _rotate(self) -> None:
        """Close the current segment durably and open the next — the
        same fsync-file-then-dir discipline ``durable_replace`` applies
        to the legacy snapshot (the ISSUE 13 durability-bugfix helper,
        reused at the segment switch)."""
        self._f.flush()
        try:
            os.fsync(self._f.fileno())
        except OSError as e:
            # matching _do_fsync's contract: a failed fsync degrades
            # LOUDLY (warning + journal_degraded) and still releases
            # flush() waiters — stalling them forever behind a sick
            # disk is exactly what degraded mode exists to avoid
            warnings.warn(f"journal fsync failed at segment rotation: "
                          f"{e!r}")
            self.degrade()
        self._f.close()
        self._seg_seq = self._next_seq()
        self._seg_path = self._seg_name(self._seg_seq)
        self._f = open(self._seg_path, "ab")
        _fsync_dir(self.path)
        self._last_sync = time.monotonic()
        self._dirty = False
        with self._lock:
            # everything written so far went down with the old
            # segment's fsync — a waiting flush(sync=True) is covered
            self._synced = max(self._synced, self._written)
            self._lock.notify_all()

    def _auto_compact_due(self) -> bool:
        return (self._live.total_units >= self.compact_min_records
                and self._live.dead_ratio > self.compact_dead_ratio)

    def _compact_io(self) -> None:
        """Writer-thread only: rewrite the live set into a fresh
        segment, fsync it durable, THEN rename every replaced segment
        to ``*.consumed`` (older consumed files are pruned — one
        forensic generation kept).  Crash-safe at every point: until
        the renames land, recovery replays old + compact segments to
        the same state (admit replaces by id)."""
        old = self._segments()
        self._f.flush()
        try:
            os.fsync(self._f.fileno())
        except OSError:
            pass
        self._f.close()
        with self._lock:
            self._synced = max(self._synced, self._written)
            self._lock.notify_all()
        seq = self._next_seq()
        self._write_compact_segment(seq, consumed=old)
        self._live.reset_accounting()
        self._seg_seq = seq + 1
        self._seg_path = self._seg_name(self._seg_seq)
        self._f = open(self._seg_path, "ab")
        _fsync_dir(self.path)
        self._dirty = False
        self._last_sync = time.monotonic()

    def _write_compact_segment(self, seq: int, consumed=()) -> None:
        path = self._seg_name(seq)
        with open(path, "wb") as f:
            # the admit entries carry their own "admitted" markers (the
            # live mirror stamps them in place), so one record type
            # round-trips the whole live set
            for e in self._live.entries.values():
                f.write(_frame(_encode({"t": "admit", "req": e})))
            f.flush()
            try:
                os.fsync(f.fileno())
            except OSError as e:
                # the compact segment is NOT provably durable: keep
                # the replaced segments (recovery replays old + this
                # one to the same state) rather than consuming the
                # only durable copy of the live set
                warnings.warn(
                    f"journal compaction fsync failed ({e!r}); "
                    "keeping the replaced segments")
                self.degrade()
                _fsync_dir(self.path)
                _compactions_total.inc()
                return
        _fsync_dir(self.path)
        # the compact segment is durable: consuming the replaced
        # segments is now safe (and re-runnable if we die mid-loop)
        for seg in consumed:
            try:
                os.replace(seg, seg + _CONSUMED_SUFFIX)
            except OSError:
                pass
        # prune consumed generations older than the ones just written
        keep = {seg + _CONSUMED_SUFFIX for seg in consumed}
        for name in os.listdir(self.path):
            p = os.path.join(self.path, name)
            if name.endswith(_CONSUMED_SUFFIX) and p not in keep:
                try:
                    os.remove(p)
                except OSError:
                    pass
        _fsync_dir(self.path)
        _compactions_total.inc()
