"""Paged-KV-cache serving for causal LMs (reference: the
block_multihead_attention serving path,
python/paddle/incubate/nn/functional/block_multihead_attention.py +
paddle/phi/kernels/fusion/gpu/block_multi_head_attention_kernel.cu).

``PagedGenerator`` drives a LlamaForCausalLM-shaped model: prefill runs
dense causal flash attention while writing K/V into fixed-size pages;
each decode step attends one token per sequence against the paged cache
via the Pallas decode kernel (ops/pallas/paged_attention.py).  Sequences
share one page pool and hold only length-proportional pages (no
rectangular max-seq allocation — the serving win the reference gets
from its block allocator); the whole batch's pages are reclaimed when
the batch finishes (per-sequence early free on EOS would change the
batch shape mid-decode and recompile — a continuous-batching scheduler
is the follow-up that needs it).
"""
from __future__ import annotations

import math
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, wrap_array
from ..framework.tape import no_grad
from ..ops.pallas.flash_attention import DEFAULT_MASK_VALUE
from ..ops.pallas.paged_attention import (PagedKVCache, _gather_dequant,
                                          dequantize_kv, paged_attention,
                                          paged_attention_multi,
                                          paged_attention_ragged,
                                          quantize_kv)
from ..testing import faults as _faults


def _maybe_lose_buffers(cache: PagedKVCache, seq_ids) -> None:
    """The ``buffer_loss`` device-fault site (ISSUE 8): when a rule
    fires here, DELETE the cache's pool buffers before re-raising, so
    the caller's ``_recover_pools`` sees consumed donated buffers and
    rebuilds the pools zeroed — the exact failure mode of a real
    device-side step fault, reproducible on CPU CI.  No plan installed
    = one ``is None`` check."""
    if _faults.active() is None:
        return
    try:
        _faults.maybe_fire("buffer_loss", seq_ids=seq_ids)
    except BaseException:
        for a in cache._device_pools():
            fn = getattr(a, "delete", None)
            if callable(fn):
                try:
                    fn()
                except Exception:   # noqa: BLE001 — already unusable
                    pass
        raise


def _fake_quant_kv(x):
    """Round-trip (quantize -> dequantize) a float K/V block through the
    int8 KV representation WITHOUT storing it: the values prefill
    attention consumes are then bit-identical to what the pages hold,
    so chunked prefill, preemption-resume, survivor replay and
    snapshot-restore stay exact in the int8 mode — a prefill that
    attended the exact in-flight suffix while decode later read the
    quantized pages would break every replay contract."""
    q, s = quantize_kv(x)
    return dequantize_kv(q, s, x.dtype)


def _tp_plan(model, mesh):
    """Megatron-style tensor-parallel placement plan for a LLaMA-shaped
    serving model over a 1-D ``('tensor',)`` mesh (ISSUE 20).

    Column-parallel (out-features on 'tensor'; weight layout is
    ``[in, out]`` so that is dim 1): q/k/v projections and the MLP
    gate/up — each chip computes its own heads / its own slice of the
    intermediate activations with NO communication.  Row-parallel
    (in-features on 'tensor', dim 0): o_proj and down_proj — their
    matmuls produce partial sums and ONE all-reduce closes each block.
    Everything else (norms, embedding, lm_head) stays replicated so the
    logits + fused sampling tail run replicated post-all-reduce.

    Returns ``(spec_by_param_id, row_parallel_layers, attn_layers)``:
    the per-param PartitionSpec map, the Linears to arm with the
    ``_tp_reduce`` hook at trace time, and the attention modules whose
    head counts are patched to their per-chip values during the trace.
    """
    from jax.sharding import PartitionSpec as P
    tp = int(mesh.size)
    layers = getattr(getattr(model, "model", None), "layers", None)
    if not layers:
        raise ValueError(
            "tensor-parallel serving needs a LLaMA-shaped model "
            "(model.model.layers with self_attn/mlp blocks)")
    spec_by_id = {}
    row_layers = []
    attn_layers = []
    col, row = P(None, "tensor"), P("tensor", None)
    for i, layer in enumerate(layers):
        attn, mlp = layer.self_attn, layer.mlp
        if attn.num_heads % tp or attn.num_kv_heads % tp:
            raise ValueError(
                f"layer {i}: num_heads ({attn.num_heads}) and "
                f"num_kv_heads ({attn.num_kv_heads}) must divide the "
                f"tensor-parallel degree ({tp})")
        if mlp.gate_proj.out_features % tp:
            raise ValueError(
                f"layer {i}: intermediate_size "
                f"({mlp.gate_proj.out_features}) must divide the "
                f"tensor-parallel degree ({tp})")
        for lin in (attn.q_proj, attn.k_proj, attn.v_proj,
                    mlp.gate_proj, mlp.up_proj):
            spec_by_id[id(lin.weight)] = col
        for lin in (attn.o_proj, mlp.down_proj):
            if lin.bias is not None:
                # a per-shard bias would be summed tp times by the
                # closing all-reduce — the serving plan only arms
                # bias-free row-parallel projections
                raise ValueError(
                    "row-parallel projections must be bias-free under "
                    "tensor parallelism")
            spec_by_id[id(lin.weight)] = row
            row_layers.append(lin)
        attn_layers.append(attn)
    return spec_by_id, row_layers, attn_layers


def fused_sample(logits, seeds, ctrs, temps, flags):
    """On-device fused sampling tail for the compiled decode/prefill
    programs: per row, greedy argmax AND a temperature categorical draw
    (threefry key = fold_in(PRNGKey(seed), ctr) — the counter is the
    token's absolute position, so a (seed, position) pair replays the
    same draw), selected by ``flags``.  All inputs are traced; only the
    (batch,) int32 token ids ever cross the host boundary.

    logits (batch, vocab) f32; seeds (batch,) uint32; ctrs (batch,)
    int32; temps (batch,) f32; flags (batch,) bool (True = sample).
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def draw(seed, ctr, row, temp):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), ctr)
        return jax.random.categorical(key,
                                      row / jnp.maximum(temp, 1e-6))

    sampled = jax.vmap(draw)(seeds, ctrs, logits, temps).astype(jnp.int32)
    return jnp.where(flags, sampled, greedy)


def _prefix_suffix_attention(q, k_suf, v_suf, k_pages, v_pages, tables,
                             prefix_lens, k_scales=None, v_scales=None):
    """Prompt-SUFFIX attention for a sequence whose prefix KV is already
    cached in pages: every suffix token attends to the whole gathered
    prefix plus the suffix causally.  Dense masked attention (the
    suffix is one bounded bucket per compile; a flash variant is a
    later kernel optimization).

    q (b, s, q_heads, d); k_suf/v_suf (b, s, kv_heads, d) post-rope;
    k/v_pages (kv_heads, total, page, d); tables (b, P) int32 pointing
    at the prefix pages; prefix_lens (b,) int32 page-aligned.
    ``k/v_scales`` (kv_heads, total, page, 1) mark int8 pages (ISSUE 9:
    dequant fused into the gather; the SUFFIX k/v must already be
    round-tripped by the caller).  Returns (b, s, q_heads, d).
    """
    b, s, qh, d = q.shape
    kvh = k_suf.shape[2]
    group = qh // kvh
    page = k_pages.shape[2]
    t_pre = tables.shape[1] * page

    def gather(pages, scales):
        # the ONE gather+dequant helper the decode/multi fallbacks use
        # — prefix-path and decode-path dequant can never drift
        return _gather_dequant(pages, scales, tables, b, kvh, t_pre, d,
                               q.dtype)

    k_all = jnp.concatenate(
        [gather(k_pages, k_scales), jnp.swapaxes(k_suf, 1, 2)],
        axis=2)                                   # (b, kvh, t_pre + s, d)
    v_all = jnp.concatenate(
        [gather(v_pages, v_scales), jnp.swapaxes(v_suf, 1, 2)],
        axis=2)
    if group != 1:
        k_all = jnp.repeat(k_all, group, axis=1)
        v_all = jnp.repeat(v_all, group, axis=1)
    qt = jnp.swapaxes(q, 1, 2)                    # (b, qh, s, d)
    scores = jnp.einsum("bhsd,bhtd->bhst", qt, k_all,
                        preferred_element_type=jnp.float32) \
        / math.sqrt(d)
    t = jnp.arange(t_pre + s, dtype=jnp.int32)
    # prefix cols: valid below the row's (page-aligned) prefix length;
    # suffix cols: causal within the suffix (right-padded bucket pads
    # sit after every real token, so causality masks them out)
    valid_pre = (t[None, :] < prefix_lens[:, None])[:, None, None, :]
    i = jnp.arange(s, dtype=jnp.int32)
    valid_suf = ((t[None, :] >= t_pre)
                 & (t[None, :] - t_pre <= i[:, None]))[None, None]
    scores = jnp.where(valid_pre | valid_suf, scores, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhst,bhtd->bhsd", p.astype(v_all.dtype), v_all)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def next_pow2(n: int) -> int:
    """Smallest power of two >= n — the shared bucketing rule for prefill
    length, decode page-table width, and the continuous-batching engine's
    running-batch size (all three must stay in sync: each bucket is one
    compiled program)."""
    b = 1
    while b < n:
        b *= 2
    return b


class _PagedContext:
    """Per-forward attention driver handed down to attention layers.

    BOTH branches are the EAGER ORACLE the jitted steps
    (JittedPagedDecoder/_TracedPagedContext) are equivalence-tested
    against — production prefill AND decode run through the compiled
    paths; keep the write/lens protocols in sync
    (tests/test_paged_attention.py eager-vs-jitted parity)."""

    def __init__(self, cache: PagedKVCache, seq_ids: Sequence[int],
                 prefill: bool):
        self.cache = cache
        self.seq_ids = list(seq_ids)
        self.prefill = prefill
        self.layer_idx = 0

    def attend(self, q: Tensor, k: Tensor, v: Tensor) -> Tensor:
        """q/k/v: (batch, s, heads, head_dim) post-rope.  Writes k/v into
        the pages, returns the attention output (batch, s, q_heads, d)."""
        cache = self.cache
        layer = self.layer_idx
        # whole batch in ONE scatter per pool (not per sequence — the
        # per-seq loop copied the full pool batch times per step)
        cache.write_batch(layer, self.seq_ids, k._data, v._data)
        if self.prefill:
            # fresh sequences: the cache holds exactly this prompt, so
            # dense causal attention over the batch is equivalent; in
            # the int8 mode the attended values must be the ROUND-
            # TRIPPED ones the pages hold, or later decode steps (which
            # read quantized pages) would diverge from this prefill
            if cache.kv_quant:
                k = wrap_array(_fake_quant_kv(k._data))
                v = wrap_array(_fake_quant_kv(v._data))
            from ..nn import functional as F
            out, _ = F.flash_attention(q, k, v, causal=True)
            return out
        tab, lens = cache.page_table(self.seq_ids)
        if layer < cache.num_layers - 1:
            # length advances when the LAST layer writes; earlier layers
            # must already count the token they just wrote
            lens = lens + k.shape[1]
        out = paged_attention(
            q._data[:, 0], cache.k_pages[layer], cache.v_pages[layer],
            lens, tab,
            k_scales=(cache.k_scales[layer] if cache.kv_quant else None),
            v_scales=(cache.v_scales[layer] if cache.kv_quant else None))
        return wrap_array(out[:, None])      # (batch, 1, q_heads, d)


class _TracedPagedContext:
    """Paged-attention driver for the JITTED decode/prefill steps: page
    pools, (page, slot) write targets, lengths and tables are all TRACED
    values carried through one compiled program — no host bookkeeping
    inside.  Scatters are functional updates on the carried pools
    (donated at the jit boundary, so XLA writes in place).

    Prefill mode: ``pg``/``sl`` are (batch*seq,) flat targets — pad
    positions carry an out-of-bounds page index, which jax scatter DROPS
    (mode 'drop' is the .at[] default), so a right-padded bucketed
    prompt never writes garbage KV; attention is dense causal flash over
    the padded batch (pads sit to the RIGHT of every real token, so
    causality keeps them out of real tokens' windows).

    Prefix-prefill mode (``prefill=True`` with ``prefix_lens`` set):
    the batch's tokens are a prompt SUFFIX whose page-aligned prefix KV
    already sits in the pages ``tables`` points at — suffix K/V scatter
    into fresh pages exactly as in prefill, but attention runs over
    [gathered prefix; suffix] so the cached tokens are visible."""

    def __init__(self, k_pages, v_pages, pg, sl, lens=None, tables=None,
                 prefill=False, prefix_lens=None, k_scales=None,
                 v_scales=None, q_lens=None):
        self.k_pages = list(k_pages)
        self.v_pages = list(v_pages)
        # int8 KV mode (ISSUE 9): parallel per-slot scale pools carried
        # through the program exactly like the data pools (donated at
        # the jit boundary); empty/None means full-precision storage
        self.k_scales = list(k_scales) if k_scales else None
        self.v_scales = list(v_scales) if v_scales else None
        self.pg = pg
        self.sl = sl
        self.lens = lens                # POST-write lengths (decode)
        self.tables = tables
        self.prefill = prefill
        self.prefix_lens = prefix_lens  # (b,) traced, prefix-prefill only
        self.q_lens = q_lens            # (b,) traced, ragged step only
        self.layer_idx = 0

    def _scatter(self, layer, ks, vs):
        """One layer's append: ``ks``/``vs`` (kvh, tokens, d) float.
        In the int8 mode quantization is FUSED into the scatter (per
        slot, per head) and the scale pools scatter alongside; returns
        the values attention must consume — the round-tripped ones, so
        every consumer sees exactly what the pages hold."""
        kp, vp = self.k_pages[layer], self.v_pages[layer]
        if self.k_scales is not None:
            k8, ksc = quantize_kv(ks)
            v8, vsc = quantize_kv(vs)
            self.k_scales[layer] = \
                self.k_scales[layer].at[:, self.pg, self.sl].set(ksc)
            self.v_scales[layer] = \
                self.v_scales[layer].at[:, self.pg, self.sl].set(vsc)
            self.k_pages[layer] = kp.at[:, self.pg, self.sl].set(k8)
            self.v_pages[layer] = vp.at[:, self.pg, self.sl].set(v8)
            return (dequantize_kv(k8, ksc, ks.dtype),
                    dequantize_kv(v8, vsc, vs.dtype))
        self.k_pages[layer] = \
            kp.at[:, self.pg, self.sl].set(ks.astype(kp.dtype))
        self.v_pages[layer] = \
            vp.at[:, self.pg, self.sl].set(vs.astype(vp.dtype))
        return ks, vs

    def _layer_scales(self, layer):
        if self.k_scales is None:
            return None, None
        return self.k_scales[layer], self.v_scales[layer]

    def attend(self, q, k, v):
        layer = self.layer_idx
        b, s = k.shape[0], k.shape[1]
        kvh, d = k.shape[2], k.shape[3]
        ks = jnp.swapaxes(k._data.reshape(b * s, kvh, d), 0, 1)
        vs = jnp.swapaxes(v._data.reshape(b * s, kvh, d), 0, 1)
        ks_att, vs_att = self._scatter(layer, ks, vs)
        ksc, vsc = self._layer_scales(layer)
        kp, vp = self.k_pages[layer], self.v_pages[layer]
        if self.prefill:
            # the suffix attends its own (round-tripped, in the int8
            # mode) values — identical to the page contents, so chunked
            # prefill and replay reproduce decode-written KV exactly
            k_att = jnp.swapaxes(ks_att, 0, 1).reshape(b, s, kvh, d)
            v_att = jnp.swapaxes(vs_att, 0, 1).reshape(b, s, kvh, d)
            if self.prefix_lens is not None:
                return wrap_array(_prefix_suffix_attention(
                    q._data, k_att, v_att, kp, vp, self.tables,
                    self.prefix_lens, k_scales=ksc, v_scales=vsc))
            from ..nn import functional as F
            out, _ = F.flash_attention(q, wrap_array(k_att),
                                       wrap_array(v_att), causal=True)
            return out
        # ragged unified step (ISSUE 17): every row attends its OWN
        # left-aligned span — decode rows, chunk spans and verify
        # blocks mix in one kernel call with per-row traced lengths
        if self.q_lens is not None:
            out = paged_attention_ragged(q._data, kp, vp, self.lens,
                                         self.q_lens, self.tables,
                                         k_scales=ksc, v_scales=vsc)
            return wrap_array(out)
        # decode / verify: s tokens per row scatter flat (s == 1 is the
        # classic decode step; s > 1 is the speculative verify block)
        if s == 1:
            out = paged_attention(q._data[:, 0], kp, vp, self.lens,
                                  self.tables, k_scales=ksc,
                                  v_scales=vsc)
            return wrap_array(out[:, None])
        out = paged_attention_multi(q._data, kp, vp, self.lens,
                                    self.tables, k_scales=ksc,
                                    v_scales=vsc)
        return wrap_array(out)


class JittedPagedDecoder:
    """One-compiled-program decode step: embed + every layer's rope /
    paged write / paged attention / MLP + logits, with the page pools
    donated through the step.  Replaces per-op eager dispatch in the
    decode hot loop (dozens of ops x layers per generated token).

    Shared by PagedGenerator and ContinuousBatchingEngine; retraces per
    (batch, pool-shape) signature and reuses the compile cache after.

    Quantized serving (ISSUE 9): ``quantize="w8"`` swaps every Linear
    projection's weight for a per-out-channel int8 twin inside the
    compiled programs (the streaming weight-only kernel;
    ``quantization.serving`` calibrates the scales through the PTQ
    observers); ``"w8a8"`` adds dynamic per-token activation
    quantization in-program.  The scales ride as TRACED arguments —
    never baked consts — so one compiled program serves any
    calibration.  An int8 cache (``PagedKVCache(kv_dtype="int8")``)
    composes orthogonally: its scale pools are donated through every
    program beside the data pools.
    """

    #: per-mode donated arg positions (page pools + scale pools) —
    #: shared between the jit call and the analysis auditor so both
    #: see one contract.  The scale-pool slots hold empty tuples (no
    #: leaves) for full-precision caches.
    DONATE_ARGNUMS = {"decode": (8, 9, 10, 11), "prefill": (6, 7, 8, 9),
                      "prefix": (8, 9, 10, 11), "verify": (8, 9, 10, 11),
                      "ragged": (9, 10, 11, 12)}

    def __init__(self, model, min_table_pages: int = 1,
                 quantize: Optional[str] = None, mesh=None,
                 tp_quant_collectives: bool = False):
        from ..quantization.serving import SERVING_QUANT_MODES
        if quantize not in SERVING_QUANT_MODES:
            raise ValueError(
                f"quantize must be one of {SERVING_QUANT_MODES}, got "
                f"{quantize!r}")
        self.model = model
        self.params = model.parameters()
        self.max_position = int(model.config.max_position_embeddings)
        self.quantize = quantize
        # tensor-parallel serving (ISSUE 20): every compiled program is
        # shard_map'd over the ('tensor',) mesh — weights land as their
        # Megatron twins, pools shard on the kv-head axis, and exactly
        # one all-reduce per block closes the row-parallel matmuls.
        # Committing the params here (device_put with NamedShardings)
        # is load-bearing three ways: each chip holds 1/tp of the
        # sharded weights, the jit input shardings are pinned so no
        # per-dispatch transfer sneaks in, and the analysis auditor's
        # engine_program_spec copies the placements into its abstract
        # args — which is what auto-triggers the tier-3 SPMD audit.
        if mesh is not None and int(mesh.size) <= 1:
            mesh = None                  # a mesh of one is the 1-chip path
        self.mesh = mesh
        self.tp = int(mesh.size) if mesh is not None else 1
        self.tp_quant_collectives = bool(tp_quant_collectives and
                                         mesh is not None)
        if mesh is not None:
            if quantize is not None:
                raise ValueError(
                    "quantize='w8'/'w8a8' does not compose with a "
                    "tensor-parallel mesh yet: the int8 weight twins "
                    "are calibrated per full out-channel and the "
                    "streaming kernel is single-chip (documented "
                    "limitation; kv_quant='int8' DOES compose)")
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            spec_by_id, self._tp_row_layers, self._tp_attn = \
                _tp_plan(model, mesh)
            self._tp_param_specs = [spec_by_id.get(id(p), P())
                                    for p in self.params]
            for p, spec in zip(self.params, self._tp_param_specs):
                p._data = jax.device_put(p._data,
                                         NamedSharding(mesh, spec))
            self._tp_reduce_fn = self._make_tp_reduce()
        else:
            self._tp_row_layers = []
            self._tp_attn = []
            self._tp_param_specs = []
            self._tp_reduce_fn = None
        if quantize is not None:
            from ..quantization.serving import quantize_linear_weights
            self._quant = quantize_linear_weights(model)
            by_id = {id(layer.weight): qi
                     for qi, (layer, _, _) in enumerate(self._quant)}
            # param-list position -> quant entry, so _param_arrays can
            # substitute the int8 twins in place
            self._quant_idx = {i: by_id[id(p)]
                               for i, p in enumerate(self.params)
                               if id(p) in by_id}
        else:
            self._quant = []
            self._quant_idx = {}
        # page-table width floor: with the default 1 the table width is
        # next_pow2(longest sequence's pages), which recompiles the
        # decode/verify/chunk programs every time the running batch
        # crosses a width bucket; pinning it at the pool's worst case
        # (ceil(max_position / page_size) rounded up) trades a bounded
        # amount of gather work for a FIXED program signature — the
        # scenario-matrix serving lane runs mixed short/long traffic
        # compile-free this way
        self.min_table_pages = max(1, int(min_table_pages))
        self._programs = {}              # (mode, sample) -> jitted fn
        self._program_fns = {}           # (mode, sample) -> raw traced fn
        self._jitted_multi = None        # built on first multi_step use

    # -------------------------------------------------- compiled programs
    def _param_arrays(self):
        """The param operands a program call ships: the model's arrays,
        with quantized Linears' weights replaced by their int8 twins —
        half (vs bf16) or a quarter (vs f32) of the weight HBM traffic
        the decode step streams."""
        if not self.quantize:
            return [p._data for p in self.params]
        return [self._quant[self._quant_idx[i]][1]
                if i in self._quant_idx else p._data
                for i, p in enumerate(self.params)]

    def _wscale_args(self):
        """Per-out-channel weight scales as one traced tuple operand
        (empty when unquantized)."""
        return tuple(s for _, _, s in self._quant)

    def _pool_args(self, cache):
        """(k_pages, v_pages, k_scales, v_scales) operand tuples — the
        scale tuples are empty for full-precision caches, so one
        program signature covers both storage modes."""
        return (tuple(cache.k_pages), tuple(cache.v_pages),
                tuple(cache.k_scales), tuple(cache.v_scales))

    @staticmethod
    def _store_pools(cache, k_pages, v_pages, k_scales, v_scales):
        cache.k_pages = list(k_pages)
        cache.v_pages = list(v_pages)
        if cache.kv_quant:
            cache.k_scales = list(k_scales)
            cache.v_scales = list(v_scales)

    def _swap_params(self, param_arrays, wscales=()):
        saved = [p._data for p in self.params]
        for p, a in zip(self.params, param_arrays):
            p._data = a
        if wscales:
            # arm the Linear hook: mode + TRACED scale per layer —
            # cleared by _restore_params so nothing leaks outside the
            # program trace
            for (layer, _, _), s in zip(self._quant, wscales):
                layer._serving_quant = (self.quantize, s)
        if self.mesh is not None:
            # TP trace arming (same trace-time pattern as the quant
            # hook): inside the shard_map body the swapped param arrays
            # are LOCAL shards, so each attention module's head counts
            # drop to their per-chip values for the duration of the
            # trace, and the row-parallel projections get the mesh
            # all-reduce that closes their partial sums
            tp = self.tp
            for attn in self._tp_attn:
                attn._tp_saved_heads = (attn.num_heads, attn.num_kv_heads)
                attn.num_heads //= tp
                attn.num_kv_heads //= tp
            for layer in self._tp_row_layers:
                layer._tp_reduce = self._tp_reduce_fn
        return saved

    def _restore_params(self, saved):
        for p, s in zip(self.params, saved):
            p._data = s
        for layer, _, _ in self._quant:
            layer._serving_quant = None
        if self.mesh is not None:
            for attn in self._tp_attn:
                attn.num_heads, attn.num_kv_heads = attn._tp_saved_heads
            for layer in self._tp_row_layers:
                layer._tp_reduce = None

    def _make_tp_reduce(self):
        """The all-reduce closing each row-parallel block: a plain f32
        ``psum`` by default, or (``tp_quant_collectives=True``) the
        EQuARX-style int8 variant — absmax-scale the local partial sum
        to s8, all-gather the int8 shards + f32 scales over 'tensor',
        dequantize and sum locally.  On the ring that moves (n-1)·S
        bytes against the f32 psum's 2·(n-1)/n·4S — 8/n fewer, the
        EQuARX 4x at tp=2 — at the cost of one absmax round-trip of
        numeric error per block, which is why it sits behind a knob
        that defaults OFF and the logits escape hatch is the parity
        oracle for it."""
        if not self.tp_quant_collectives:
            return lambda x: jax.lax.psum(x, "tensor")
        tp = self.tp

        def quant_psum(x):
            amax = jnp.max(jnp.abs(x))
            scale = jnp.maximum(amax, 1e-8) / 127.0
            q = jnp.clip(jnp.round(x / scale),
                         -127.0, 127.0).astype(jnp.int8)
            qg = jax.lax.all_gather(q, "tensor")        # (tp, ...) s8
            sg = jax.lax.all_gather(scale, "tensor")    # (tp,) f32
            return jnp.sum(
                qg.astype(x.dtype)
                * sg.astype(x.dtype).reshape((tp,) + (1,) * x.ndim),
                axis=0)

        return quant_psum

    #: replicated positional args between ``param_arrays`` and the pool
    #: tuple, per program mode — the shard_map in_specs contract
    #: (everything host-shaped rides replicated; pools shard on the
    #: kv-head axis; the param list gets its per-param spec list)
    _TP_N_REPLICATED = {"decode": 7, "prefill": 5, "prefix": 7,
                        "verify": 7, "ragged": 8}

    def _mesh_wrap(self, mode, fn):
        """shard_map a program body over the tensor mesh (identity on
        the 1-chip decoder).  in/out specs are pytree prefixes: P()
        broadcasts over the sampling tuple and the (possibly empty)
        wscales tuple, P('tensor') over each per-layer pool tuple —
        rank-4 pools shard dim 0, the kv-head axis.  Replication checks
        are off (the compat wrapper maps check_vma across jax
        versions): the outputs ARE replicated by construction — every
        chip holds the full hidden state after each block's closing
        all-reduce, so logits, accept arithmetic and the fused sampling
        tail compute identically everywhere."""
        if self.mesh is None:
            return fn
        from jax.sharding import PartitionSpec as P
        from ..framework.jax_compat import shard_map
        rep, pool = P(), P("tensor")
        in_specs = (list(self._tp_param_specs),
                    *([rep] * self._TP_N_REPLICATED[mode]),
                    pool, pool, pool, pool, rep)
        n_out = 2 if mode in ("verify", "ragged") else 1
        out_specs = (*([rep] * n_out), pool, pool, pool, pool)
        return shard_map(fn, mesh=self.mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)

    def _program(self, mode: str, sample):
        """Lazily build one compiled program per (mode, sample) pair.
        ``sample`` is the static tail kind: "draw" ends in the full
        fused_sample tail, "greedy" in a bare argmax (same (batch,)
        int32 host transfer, none of the threefry/categorical compute —
        all-greedy batches are the serving default), and False keeps
        returning full last-token logits (the escape hatch the
        eager-oracle parity tests diff against)."""
        key = (mode, sample)
        prog = self._programs.get(key)
        if prog is not None:
            return prog
        model = self.model

        def tail(logits, sampling):
            if sample == "draw":
                return fused_sample(logits, *sampling)
            if sample == "greedy":
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            return logits

        def last_logits(hidden, last_idx):
            # per-row last REAL position (bucketed prompts are
            # right-padded past it)
            b = hidden.shape[0]
            last = hidden._data[jnp.arange(b), last_idx.astype(jnp.int32)]
            logits = model._logits_of(wrap_array(last[:, None]))
            return logits._data[:, -1].astype(jnp.float32)

        def ctx_pools(ctx):
            return (tuple(ctx.k_pages), tuple(ctx.v_pages),
                    tuple(ctx.k_scales or ()), tuple(ctx.v_scales or ()))

        if mode == "decode":
            def fn(param_arrays, tokens, pos, pg, sl, lens, tables,
                   sampling, k_pages, v_pages, k_scales, v_scales,
                   wscales):
                saved = self._swap_params(param_arrays, wscales)
                try:
                    ctx = _TracedPagedContext(k_pages, v_pages, pg, sl,
                                              lens, tables,
                                              k_scales=k_scales,
                                              v_scales=v_scales)
                    with no_grad():
                        hidden = model.model(wrap_array(tokens), pos,
                                             paged_ctx=ctx)
                        logits = model._logits_of(hidden)
                    return (tail(logits._data[:, -1].astype(jnp.float32),
                                 sampling),
                            *ctx_pools(ctx))
                finally:
                    self._restore_params(saved)

        elif mode == "prefill":
            def fn(param_arrays, ids, last_idx, pg, sl, sampling,
                   k_pages, v_pages, k_scales, v_scales, wscales):
                saved = self._swap_params(param_arrays, wscales)
                try:
                    ctx = _TracedPagedContext(k_pages, v_pages, pg, sl,
                                              prefill=True,
                                              k_scales=k_scales,
                                              v_scales=v_scales)
                    with no_grad():
                        hidden = model.model(wrap_array(ids), 0,
                                             paged_ctx=ctx)
                        logits = last_logits(hidden, last_idx)
                    return (tail(logits, sampling), *ctx_pools(ctx))
                finally:
                    self._restore_params(saved)

        elif mode == "prefix":
            def fn(param_arrays, ids, last_idx, pg, sl, ptabs,
                   plens, sampling, k_pages, v_pages, k_scales,
                   v_scales, wscales):
                saved = self._swap_params(param_arrays, wscales)
                try:
                    ctx = _TracedPagedContext(k_pages, v_pages, pg, sl,
                                              tables=ptabs, prefill=True,
                                              prefix_lens=plens,
                                              k_scales=k_scales,
                                              v_scales=v_scales)
                    with no_grad():
                        # plens doubles as the per-row rope offset: the
                        # suffix starts right after the cached prefix
                        # (traced, so one compile serves every prefix
                        # length at a given bucket shape)
                        hidden = model.model(wrap_array(ids), plens,
                                             paged_ctx=ctx)
                        logits = last_logits(hidden, last_idx)
                    return (tail(logits, sampling), *ctx_pools(ctx))
                finally:
                    self._restore_params(saved)

        elif mode == "verify":
            def fn(param_arrays, block, pos, pg, sl, lens, tables,
                   sampling, k_pages, v_pages, k_scales, v_scales,
                   wscales):
                """Speculative-decoding verify: ONE compiled dispatch
                scores the whole (B, S) block — S = 1 fed token + k
                draft proposals — against paged KV + the in-flight
                block suffix (ragged multi-query attention), computes
                per-row ACCEPT LENGTHS on device, and fuses the bonus
                token's sampling, so the host boundary stays (batch,)
                ids + (batch,) accept counts whatever k is."""
                saved = self._swap_params(param_arrays, wscales)
                try:
                    ctx = _TracedPagedContext(k_pages, v_pages, pg, sl,
                                              lens, tables,
                                              k_scales=k_scales,
                                              v_scales=v_scales)
                    with no_grad():
                        hidden = model.model(wrap_array(block), pos,
                                             paged_ctx=ctx)
                        logits = model._logits_of(hidden)
                    lg = logits._data.astype(jnp.float32)   # (B, S, V)
                    # targets[b, s] = the target's own next token after
                    # block[b, :s+1] — the greedy-exactness oracle
                    targets = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    match = (block[:, 1:] == targets[:, :-1]) \
                        .astype(jnp.int32)
                    accept = jnp.sum(jnp.cumprod(match, axis=1),
                                     axis=1).astype(jnp.int32)  # (B,)
                    pools = ctx_pools(ctx)
                    if sample == "greedy":
                        ids = jnp.take_along_axis(
                            targets, accept[:, None], axis=1)[:, 0]
                        return ids, accept, *pools
                    bonus = jnp.take_along_axis(
                        lg, accept[:, None, None], axis=1)[:, 0]
                    if sample == "draw":
                        seeds, temps, flags = sampling
                        # the bonus token's absolute position — sampled
                        # rows ride with accept == 0 (host feeds them
                        # unmatched draft slots), so this replays the
                        # plain decode path's (seed, position) draw
                        ctrs = pos + accept + 1
                        ids = fused_sample(bonus, seeds, ctrs, temps,
                                           flags)
                        return ids, accept, *pools
                    return bonus, accept, *pools   # logits escape hatch
                finally:
                    self._restore_params(saved)

        elif mode == "ragged":
            def fn(param_arrays, ids, ctx_lens, q_lens, pg, sl, tables,
                   nd, sampling, k_pages, v_pages, k_scales, v_scales,
                   wscales):
                """Ragged UNIFIED serving step (ISSUE 17): one compiled
                dispatch processes a batch mixing decode rows
                (q_len 1), prefill/chunk spans, and speculative verify
                blocks (q_len = nd + 1).  Each row's span sits
                LEFT-aligned in the (B, S) bucket; ``ctx_lens`` is the
                pre-write cached length (doubling as the per-row rope
                offset), ``q_lens`` the span length, ``nd`` the draft
                count (0 for non-verify rows, which makes the accept
                arithmetic degenerate to 'pick the last real token').
                Accept lengths and the output token's position select
                ON DEVICE, so the host boundary stays (B,) ids + (B,)
                accepts whatever the batch mixes."""
                saved = self._swap_params(param_arrays, wscales)
                try:
                    ctx = _TracedPagedContext(k_pages, v_pages, pg, sl,
                                              ctx_lens + q_lens, tables,
                                              q_lens=q_lens,
                                              k_scales=k_scales,
                                              v_scales=v_scales)
                    with no_grad():
                        hidden = model.model(wrap_array(ids), ctx_lens,
                                             paged_ctx=ctx)
                        logits = model._logits_of(hidden)
                    lg = logits._data.astype(jnp.float32)   # (B, S, V)
                    targets = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                    # verify-row accept arithmetic, gated to the first
                    # nd positions so chunk/decode rows (nd == 0) can
                    # never 'accept' their own prompt tokens
                    j = jnp.arange(1, ids.shape[1],
                                   dtype=jnp.int32)[None, :]
                    match = ((ids[:, 1:] == targets[:, :-1])
                             & (j <= nd[:, None])).astype(jnp.int32)
                    accept = jnp.sum(jnp.cumprod(match, axis=1),
                                     axis=1).astype(jnp.int32)  # (B,)
                    # the row's OUTPUT position: last real token for
                    # decode/chunk rows (q_lens - 1), the bonus
                    # position (accept) for verify rows
                    sel = (q_lens - 1 - nd + accept).astype(jnp.int32)
                    pools = ctx_pools(ctx)
                    if sample == "greedy":
                        ids_out = jnp.take_along_axis(
                            targets, sel[:, None], axis=1)[:, 0]
                        return ids_out, accept, *pools
                    lg_sel = jnp.take_along_axis(
                        lg, sel[:, None, None], axis=1)[:, 0]
                    if sample == "draw":
                        seeds, temps, flags = sampling
                        # absolute position of the emitted token —
                        # ctx + q_len for decode/chunk rows, the
                        # bonus position ctx + accept + 1 for verify
                        # rows: the SAME (seed, position) threefry
                        # draw every legacy mode replays
                        ctrs = (ctx_lens + q_lens - nd
                                + accept).astype(jnp.int32)
                        ids_out = fused_sample(lg_sel, seeds, ctrs,
                                               temps, flags)
                        return ids_out, accept, *pools
                    return lg_sel, accept, *pools  # logits escape hatch
                finally:
                    self._restore_params(saved)

        else:
            raise ValueError(f"unknown program mode {mode!r}")
        # TP: the shard_map wrapping applies to the RAW fn so the
        # auditor's program_fn trace sees the sharded program too —
        # donation stays at the jit level, aliasing the global sharded
        # pool buffers through the step exactly as on one chip
        fn = self._mesh_wrap(mode, fn)
        prog = jax.jit(fn, donate_argnums=self.DONATE_ARGNUMS[mode])
        self._program_fns[key] = fn
        self._programs[key] = prog
        return prog

    def program_fn(self, mode: str, sample):
        """(raw traced fn, donate_argnums) for a program — the analysis
        auditor's entry: ``jax.make_jaxpr`` over this fn with abstract
        args sees exactly what the jitted program compiles, without
        running anything (paddle_tpu.analysis.audit_engine)."""
        self._program(mode, sample)
        return self._program_fns[(mode, sample)], \
            self.DONATE_ARGNUMS[mode]

    @staticmethod
    def _recover_pools(cache):
        """After a failed compiled call, rebuild the page pools ONLY if
        the donated buffers were actually consumed (dispatch reached
        the device/runtime).  A host-side failure before dispatch — a
        planning bug, an injected fault, a shape error — leaves them
        valid, and keeping them preserves every OTHER sequence's cached
        KV and the prefix index: the quarantine machinery (ISSUE 4)
        depends on a poisoned request not zeroing its batchmates'
        state."""
        def dead(a):
            fn = getattr(a, "is_deleted", None)
            try:
                return bool(fn()) if callable(fn) else False
            except Exception:   # noqa: BLE001 — treat unknown as dead
                return True
        if any(dead(a) for a in cache._device_pools()):
            cache.reset_pools()

    def _rollback_lengths(self, cache, seq_ids, before):
        """Undo this call's ``advance`` after a failed compiled step so
        the sequences sit at their pre-call lengths and the SAME step
        can be retried (ISSUE 4 failure isolation: the engine's
        retry/bisect replays depend on this).  Pages allocated for the
        call stay mapped — they are within the admission reservation
        and the retry rewrites their slots."""
        for sid, n in zip(seq_ids, before):
            cache.truncate(sid, n)

    @staticmethod
    def _sampling_args(sampling):
        if sampling is None:
            return False, ()
        seeds, ctrs, temps, flags = sampling
        if not np.any(flags):
            return "greedy", ()      # argmax-only tail, no RNG compute
        return "draw", (jnp.asarray(np.asarray(seeds, np.uint32)),
                        jnp.asarray(np.asarray(ctrs, np.int32)),
                        jnp.asarray(np.asarray(temps, np.float32)),
                        jnp.asarray(np.asarray(flags, bool)))

    @staticmethod
    def _pad_prefill_plan(cache, ids_np, pg, sl, b, s, s_b):
        """Right-pad a bucketed prompt's ids and (page, slot) targets;
        pad positions scatter to an out-of-bounds page (dropped)."""
        pad = s_b - s
        ids_np = np.pad(ids_np, ((0, 0), (0, pad)))
        pg = np.concatenate(
            [pg.reshape(b, s),
             np.full((b, pad), cache.total_pages, np.int32)],
            axis=1).reshape(-1)
        sl = np.concatenate(
            [sl.reshape(b, s), np.zeros((b, pad), np.int32)],
            axis=1).reshape(-1)
        return ids_np, pg, sl

    def prefill(self, cache: PagedKVCache, seq_ids, ids_np,
                bucket: bool = False, sampling=None) -> np.ndarray:
        """Prompt pass as ONE compiled program: embed + all layers
        (dense causal flash + paged KV writes) + last-token logits.

        ids_np (batch, s) int32, all rows the same real length s.  With
        ``bucket=True`` the sequence pads right to a power of two so the
        engine's per-request prefills compile once per bucket, not once
        per prompt length; pad positions scatter to an out-of-bounds
        page (dropped) and sit after every real token (causal-masked).
        Returns last-real-token logits (batch, vocab) float32 — or,
        with ``sampling=(seeds, ctrs, temps, flags)``, the fused-sampled
        first token ids (batch,) int32 (the logits never leave device).
        """
        b, s = ids_np.shape
        if s > self.max_position:
            raise ValueError(
                f"prompt length {s} exceeds max_position_embeddings "
                f"({self.max_position})")
        before = [cache.length(sid) for sid in seq_ids]
        for sid in seq_ids:
            cache.allocate(sid, s)
        pg, sl = cache.plan_write(seq_ids, s)
        cache.advance(seq_ids, s)
        s_b = s
        if bucket:
            # never pad past the rope table: a 600-token prompt on a
            # 1000-position model must bucket to 1000, not 1024
            s_b = min(next_pow2(s), self.max_position)
        if s_b != s:
            ids_np, pg, sl = self._pad_prefill_plan(cache, ids_np, pg, sl,
                                                    b, s, s_b)
        last_idx = np.full(b, s - 1, np.int32)
        sample, s_args = self._sampling_args(sampling)
        try:
            _maybe_lose_buffers(cache, seq_ids)
            out, *pools = self._program("prefill", sample)(
                self._param_arrays(),
                jnp.asarray(ids_np.astype(np.int32)),
                jnp.asarray(last_idx), jnp.asarray(pg), jnp.asarray(sl),
                s_args, *self._pool_args(cache), self._wscale_args())
        except BaseException:
            self._recover_pools(cache)
            self._rollback_lengths(cache, seq_ids, before)
            raise
        self._store_pools(cache, *pools)
        return np.asarray(out)

    def prefix_prefill(self, cache: PagedKVCache, seq_ids, ids_np,
                       prefix_tokens: int, bucket: bool = True,
                       sampling=None) -> np.ndarray:
        """Suffix-only prompt pass for sequences whose first
        ``prefix_tokens`` prompt tokens (page-aligned) are already
        cached — the prefix-cache TTFT win: only the suffix runs
        through the model, attending to the gathered prefix pages.

        Every sequence must already hold its shared prefix pages at
        length ``prefix_tokens`` (PagedKVCache.acquire_prefix).  ids_np
        (batch, s) int32 is the UNCACHED prompt tail.  Returns logits
        (batch, vocab) f32, or sampled ids (batch,) with ``sampling``.
        """
        k = int(prefix_tokens)
        if k <= 0 or k % cache.page_size:
            raise ValueError(
                f"prefix_tokens must be a positive multiple of the page "
                f"size ({cache.page_size}), got {k}")
        return self._context_prefill(cache, seq_ids, ids_np, k, bucket,
                                     sampling)

    def chunk_prefill(self, cache: PagedKVCache, seq_ids, ids_np,
                      context_tokens: int, bucket: bool = True,
                      sampling=None) -> np.ndarray:
        """Chunked-prefill continuation (ISSUE 7): ingest the next
        ``ids_np`` (batch, s) slice of a prompt whose first
        ``context_tokens`` tokens are already in the cache, at ANY
        length — unlike :meth:`prefix_prefill` the context need not be
        page-aligned, because the sequence OWNS its pages (a partially
        filled page is never shared; the chunk's first tokens simply
        fill its remaining slots).  Same compiled program as the
        prefix path (the context length is traced), so interleaving
        chunk sizes never multiplies program count."""
        k = int(context_tokens)
        if k <= 0:
            raise ValueError(
                f"context_tokens must be positive, got {k} (use "
                "prefill() for a fresh sequence)")
        return self._context_prefill(cache, seq_ids, ids_np, k, bucket,
                                     sampling)

    def _context_prefill(self, cache: PagedKVCache, seq_ids, ids_np,
                         k: int, bucket: bool, sampling) -> np.ndarray:
        b, s = ids_np.shape
        if k + s > self.max_position:
            raise ValueError(
                f"prompt length {k + s} exceeds max_position_embeddings "
                f"({self.max_position})")
        before = []
        for sid in seq_ids:
            if cache.length(sid) != k:
                raise ValueError(
                    f"sequence {sid!r} is at length {cache.length(sid)}, "
                    f"expected the cached context length {k}")
            before.append(cache.length(sid))
            cache.allocate(sid, s)
        pg, sl = cache.plan_write(seq_ids, s)
        cache.advance(seq_ids, s)
        s_b = min(next_pow2(s), self.max_position - k) if bucket else s
        if s_b != s:
            ids_np, pg, sl = self._pad_prefill_plan(cache, ids_np, pg, sl,
                                                    b, s, s_b)
        # the context may end mid-page (chunked prefill): gather the
        # partial page too — attention masks cols past k, and this
        # chunk's own tokens reach themselves through the suffix path
        n_pre = -(-k // cache.page_size)
        ptabs = np.zeros(
            (b, max(next_pow2(n_pre), self.min_table_pages)), np.int32)
        for i, sid in enumerate(seq_ids):
            ptabs[i, :n_pre] = cache._seq_pages[sid][:n_pre]
        plens = np.full(b, k, np.int32)
        last_idx = np.full(b, s - 1, np.int32)
        sample, s_args = self._sampling_args(sampling)
        return self._dispatch_prefix(
            cache, seq_ids, before, sample, s_args,
            ids_np.astype(np.int32), last_idx, pg, sl, ptabs, plens)

    def _dispatch_prefix(self, cache, seq_ids, before, sample, s_args,
                         ids, last_idx, pg, sl, ptabs, plens):
        """The "prefix" program's dispatch + failure-recovery contract,
        shared by the uniform-context and batched (per-row ``ks``)
        prefill paths: on ANY failure the donated pools are recovered
        and the advanced lengths roll back to ``before`` — one
        implementation, so the recovery semantics can never drift
        between the two builders."""
        try:
            _maybe_lose_buffers(cache, seq_ids)
            out, *pools = self._program("prefix", sample)(
                self._param_arrays(), jnp.asarray(ids),
                jnp.asarray(last_idx),
                jnp.asarray(pg), jnp.asarray(sl), jnp.asarray(ptabs),
                jnp.asarray(plens), s_args,
                *self._pool_args(cache), self._wscale_args())
        except BaseException:
            self._recover_pools(cache)
            self._rollback_lengths(cache, seq_ids, before)
            raise
        self._store_pools(cache, *pools)
        return np.asarray(out)

    def batch_context_prefill(self, cache: PagedKVCache, seq_ids, rows,
                              ks, sampling=None) -> np.ndarray:
        """Batched context-prefill continuation (ISSUE 9 satellite:
        batched survivor replay): ingest ``rows[i]`` (a 1-D int32 token
        slice) for ``seq_ids[i]`` whose cached context length is
        ``ks[i]`` — ONE compiled dispatch for the whole batch, through
        the SAME traced "prefix" program the chunked/prefix prefill
        paths compile (context lengths and rope offsets are per-row
        TRACED values, so mixed-progress rows batch together).

        Rows right-pad to a power-of-two bucket (pad positions scatter
        to the dropped out-of-bounds page and are causality/last_idx-
        masked); ``ks[i] == 0`` rows ride the same program — a zero
        prefix length masks every prefix column, making the dispatch a
        fresh prefill for that row.  Returns the last-real-token output
        per row (ids under ``sampling``, logits otherwise)."""
        b = len(seq_ids)
        ns = [len(r) for r in rows]
        if b == 0 or min(ns) < 1:
            raise ValueError("every row needs at least one token")
        before = []
        for sid, k, n in zip(seq_ids, ks, ns):
            if cache.length(sid) != int(k):
                raise ValueError(
                    f"sequence {sid!r} is at length {cache.length(sid)}, "
                    f"expected the cached context length {k}")
            if int(k) + n > self.max_position:
                raise ValueError(
                    f"context {k} + chunk {n} exceeds "
                    f"max_position_embeddings ({self.max_position})")
            before.append(int(k))
            cache.allocate(sid, n)
        # never pad past the rope table when the bucket round-up is
        # what crosses it: clamp the bucket by the deepest context,
        # the SAME ``min(next_pow2(s), max_position - k)`` discipline
        # as _context_prefill — falling all the way back to the raw
        # max(ns) would trace a fresh prefix program per distinct
        # chunk length on the MTTR-critical recovery path.  With MIXED
        # context lengths a shallow-context row can still force
        # s_b > max_position - k for a DEEPER row (each row alone
        # validated k + n <= max_position) — that row's pad positions
        # gather CLAMPED rope angles, which is safe by construction:
        # pad K/V scatters to the dropped out-of-bounds page, pad
        # columns are causality-masked, and pad rows' outputs are
        # discarded (last_idx picks the real last token) — but nothing
        # downstream may ever start reading pad-position outputs.
        s_b = max(max(ns),
                  min(next_pow2(max(ns)),
                      self.max_position - max(int(k) for k in ks)))
        # the BATCH dimension buckets too (the decode path's
        # discipline): recovery waves of 3 and 4 survivors must share
        # one compiled (b, s_b, W) shape, not trace a fresh prefix
        # program per distinct survivor count on the MTTR-critical
        # path.  Pad rows have no sequence: their scatters drop on the
        # out-of-bounds page, plens 0 masks every prefix column, and
        # their outputs are sliced off before returning.
        b_b = next_pow2(b)
        ids = np.zeros((b_b, s_b), np.int32)
        pg = np.full((b_b, s_b), cache.total_pages, np.int32)  # drop
        sl = np.zeros((b_b, s_b), np.int32)
        for i, (sid, row, n) in enumerate(zip(seq_ids, rows, ns)):
            ids[i, :n] = np.asarray(row, np.int32)
            rpg, rsl = cache.plan_write([sid], n)
            pg[i, :n] = rpg
            sl[i, :n] = rsl
            cache.advance([sid], n)
        n_pre = max(1, max(-(-int(k) // cache.page_size) for k in ks))
        W = max(next_pow2(n_pre), self.min_table_pages)
        ptabs = np.zeros((b_b, W), np.int32)
        for i, (sid, k) in enumerate(zip(seq_ids, ks)):
            npg = -(-int(k) // cache.page_size)
            ptabs[i, :npg] = cache._seq_pages[sid][:npg]
        plens = np.zeros(b_b, np.int32)
        plens[:b] = np.asarray(ks, np.int32)
        last_idx = np.zeros(b_b, np.int32)
        last_idx[:b] = np.asarray([n - 1 for n in ns], np.int32)
        if sampling is not None and b_b != b:
            seeds, ctrs, temps, flags = sampling
            pad = b_b - b
            sampling = (
                np.concatenate([np.asarray(seeds, np.uint32),
                                np.zeros(pad, np.uint32)]),
                np.concatenate([np.asarray(ctrs, np.int32),
                                np.zeros(pad, np.int32)]),
                np.concatenate([np.asarray(temps, np.float32),
                                np.ones(pad, np.float32)]),
                np.concatenate([np.asarray(flags, bool),
                                np.zeros(pad, bool)]))
        sample, s_args = self._sampling_args(sampling)
        out = self._dispatch_prefix(
            cache, seq_ids, before, sample, s_args,
            ids, last_idx, pg.reshape(-1), sl.reshape(-1), ptabs, plens)
        return out[:b]

    @staticmethod
    def _verify_sampling_args(sampling):
        """Verify-tail variant of ``_sampling_args``: no host-side
        counters — the bonus draw's position is ``pos + accept + 1``,
        computed IN-PROGRAM from the device-side accept length."""
        if sampling is None:
            return False, ()
        seeds, temps, flags = sampling
        if not np.any(flags):
            return "greedy", ()
        return "draw", (jnp.asarray(np.asarray(seeds, np.uint32)),
                        jnp.asarray(np.asarray(temps, np.float32)),
                        jnp.asarray(np.asarray(flags, bool)))

    def verify(self, cache: PagedKVCache, seq_ids, block_np,
               positions_np, sampling=None):
        """Speculative verify: score a (batch, S) token block — each
        row's last fed token followed by S-1 draft proposals — in ONE
        compiled multi-token step over the paged cache, replacing S-1
        bandwidth-bound decode dispatches with one compute-dense pass.

        block_np (batch, S) int32; positions_np (batch,) int32 — each
        row's current length (the block's first rope position).  All S
        positions' KV are written and the lengths advance by S; the
        CALLER rolls back to the verified length with
        ``cache.truncate(sid, pos + accept + 1)`` (the page-granular
        partial rollback — pages stay mapped inside the admission
        reservation, rejected slots are simply rewritten later).

        Returns ``(out, accept)``: ``accept`` (batch,) int32 counts the
        leading draft tokens the target reproduced; ``out`` is the
        bonus token ids (batch,) int32 under fused sampling, or the
        bonus position's logits row (batch, vocab) f32 on the
        ``sampling=None`` escape hatch.  With ``sampling=(seeds,
        temps, flags)`` sampled rows draw at position pos+accept+1 with
        the same (seed, position) threefry key the plain decode path
        uses."""
        b, s = block_np.shape
        if int(positions_np.max()) + s > self.max_position:
            raise ValueError(
                f"verify through position {int(positions_np.max()) + s} "
                f"exceeds max_position_embeddings ({self.max_position})")
        before = [cache.length(sid) for sid in seq_ids]
        # all-or-nothing: mid-batch exhaustion must not strand rows
        cache.allocate_batch_atomic(seq_ids, s)
        pg, sl = cache.plan_write(seq_ids, s)
        cache.advance(seq_ids, s)
        needed = max(len(cache._seq_pages.get(sid, ()))
                     for sid in seq_ids)
        tabs, lens = cache.page_table(
            seq_ids, max_pages=max(next_pow2(needed),
                                   self.min_table_pages))
        sample, s_args = self._verify_sampling_args(sampling)
        try:
            _maybe_lose_buffers(cache, seq_ids)
            out, accept, *pools = self._program(
                "verify", sample)(
                self._param_arrays(),
                jnp.asarray(block_np.astype(np.int32)),
                jnp.asarray(positions_np.astype(np.int32)),
                jnp.asarray(pg), jnp.asarray(sl), lens, tabs, s_args,
                *self._pool_args(cache), self._wscale_args())
        except BaseException:
            self._recover_pools(cache)
            self._rollback_lengths(cache, seq_ids, before)
            raise
        self._store_pools(cache, *pools)
        return np.asarray(out), np.asarray(accept)

    def ragged_step(self, cache: PagedKVCache, seq_ids, rows, ctxs,
                    n_drafts=None, sampling=None):
        """ONE compiled dispatch for a RAGGED serving step (ISSUE 17):
        ``rows[i]`` is a 1-D int32 token span for ``seq_ids[i]`` whose
        cached context length is ``ctxs[i]`` — a decode row is the one
        last-sampled token, a prefill/chunk row is the next prompt
        slice, a speculative verify row is the last fed token followed
        by ``n_drafts[i]`` draft proposals.  All rows run through the
        single "ragged" program: per-row traced context lengths, span
        lengths and draft counts, so ANY mix compiles once per
        (B, S, W) bucket.

        Spans right-pad to a power-of-two bucket (pad positions scatter
        to the dropped out-of-bounds page; the ragged kernel clamps pad
        queries at the row's kv length — finite garbage, discarded) and
        the batch pads with ctx-0 single-token rows exactly like
        ``batch_context_prefill``.  Page allocation is all-or-nothing
        across the batch (per-row counts), and on ANY failure the
        donated pools recover and every length rolls back to ``ctxs``
        so the engine can replay or decompose the step.

        Returns ``(out, accept)`` for the real rows: ``accept[i]``
        counts the leading draft tokens the target reproduced (0 for
        non-verify rows); ``out`` is the emitted token ids (batch,)
        int32 under ``sampling=(seeds, temps, flags)`` / greedy, or the
        selected position's logits rows on the ``sampling=None`` escape
        hatch.  The CALLER rolls verify rows back to their accepted
        length with ``cache.truncate`` (same contract as
        :meth:`verify`)."""
        b = len(seq_ids)
        ns = [len(r) for r in rows]
        if b == 0 or min(ns) < 1:
            raise ValueError("every row needs at least one token")
        nds = [0] * b if n_drafts is None else [int(x) for x in n_drafts]
        before = []
        for sid, k, n, nd in zip(seq_ids, ctxs, ns, nds):
            if nd and n != nd + 1:
                raise ValueError(
                    f"verify row for {sid!r} must be 1 fed token + "
                    f"{nd} drafts, got {n} tokens")
            if cache.length(sid) != int(k):
                raise ValueError(
                    f"sequence {sid!r} is at length {cache.length(sid)}, "
                    f"expected the cached context length {k}")
            if int(k) + n > self.max_position:
                raise ValueError(
                    f"context {k} + span {n} exceeds "
                    f"max_position_embeddings ({self.max_position})")
            before.append(int(k))
        # all-or-nothing page reservation with PER-ROW counts: a
        # mid-batch exhaustion must not strand earlier rows' pages
        cache.allocate_batch_atomic(seq_ids, ns)
        # span bucket: clamp by the deepest context (the
        # batch_context_prefill discipline) so the round-up never walks
        # pad positions past the rope table on its own
        s_b = max(max(ns),
                  min(next_pow2(max(ns)),
                      self.max_position - max(int(k) for k in ctxs)))
        b_b = next_pow2(b)
        ids = np.zeros((b_b, s_b), np.int32)
        pg = np.full((b_b, s_b), cache.total_pages, np.int32)  # drop
        sl = np.zeros((b_b, s_b), np.int32)
        for i, (sid, row, n) in enumerate(zip(seq_ids, rows, ns)):
            ids[i, :n] = np.asarray(row, np.int32)
            rpg, rsl = cache.plan_write([sid], n)
            pg[i, :n] = rpg
            sl[i, :n] = rsl
            cache.advance([sid], n)
        needed = max(len(cache._seq_pages.get(sid, ()))
                     for sid in seq_ids)
        W = max(next_pow2(needed), self.min_table_pages)
        tabs = np.zeros((b_b, W), np.int32)
        for i, sid in enumerate(seq_ids):
            t = cache._seq_pages[sid]
            tabs[i, :len(t)] = t
        ctx_arr = np.zeros(b_b, np.int32)
        ctx_arr[:b] = np.asarray([int(k) for k in ctxs], np.int32)
        ql = np.ones(b_b, np.int32)          # pad rows: 1-token span,
        ql[:b] = np.asarray(ns, np.int32)    # ctx 0, dropped scatter
        nd_arr = np.zeros(b_b, np.int32)
        nd_arr[:b] = np.asarray(nds, np.int32)
        if sampling is not None and b_b != b:
            seeds, temps, flags = sampling
            pad = b_b - b
            sampling = (
                np.concatenate([np.asarray(seeds, np.uint32),
                                np.zeros(pad, np.uint32)]),
                np.concatenate([np.asarray(temps, np.float32),
                                np.ones(pad, np.float32)]),
                np.concatenate([np.asarray(flags, bool),
                                np.zeros(pad, bool)]))
        sample, s_args = self._verify_sampling_args(sampling)
        try:
            _maybe_lose_buffers(cache, seq_ids)
            out, accept, *pools = self._program("ragged", sample)(
                self._param_arrays(), jnp.asarray(ids),
                jnp.asarray(ctx_arr), jnp.asarray(ql),
                jnp.asarray(pg.reshape(-1)), jnp.asarray(sl.reshape(-1)),
                jnp.asarray(tabs), jnp.asarray(nd_arr), s_args,
                *self._pool_args(cache), self._wscale_args())
        except BaseException:
            self._recover_pools(cache)
            self._rollback_lengths(cache, seq_ids, before)
            raise
        self._store_pools(cache, *pools)
        return np.asarray(out)[:b], np.asarray(accept)[:b]

    def _build_multi(self):
        """Jitted N-step GREEDY decode: lax.scan over the single-step
        body with the page pools as carry — N tokens per host dispatch
        instead of one.  On a tunnelled deployment each dispatch costs
        milliseconds of RPC latency; fusing the loop removes all but one
        of those round trips per chunk (and on local hardware removes
        N-1 host synchronizations)."""
        import jax
        from jax import lax

        def multi_fn(param_arrays, tokens0, pg_steps, sl_steps, pos_steps,
                     tables, k_pages, v_pages, k_scales, v_scales,
                     wscales):
            saved = self._swap_params(param_arrays, wscales)
            try:
                def body(carry, xs):
                    toks, kp, vp, ksc, vsc = carry
                    pg, sl, pos = xs
                    ctx = _TracedPagedContext(
                        list(kp), list(vp), pg, sl, pos + 1, tables,
                        k_scales=ksc, v_scales=vsc)
                    with no_grad():
                        hidden = self.model.model(
                            wrap_array(toks[:, None]), pos, paged_ctx=ctx)
                        logits = self.model._logits_of(hidden)
                    nxt = jnp.argmax(
                        logits._data[:, -1].astype(jnp.float32),
                        axis=-1).astype(jnp.int32)
                    return ((nxt, tuple(ctx.k_pages), tuple(ctx.v_pages),
                             tuple(ctx.k_scales or ()),
                             tuple(ctx.v_scales or ())),
                            nxt)

                (last, kp, vp, ksc, vsc), toks = lax.scan(
                    body,
                    (tokens0, tuple(k_pages), tuple(v_pages),
                     tuple(k_scales), tuple(v_scales)),
                    (pg_steps, sl_steps, pos_steps))
                return toks, kp, vp, ksc, vsc
            finally:
                self._restore_params(saved)

        if self.mesh is not None:
            from jax.sharding import PartitionSpec as P
            from ..framework.jax_compat import shard_map
            rep, pool = P(), P("tensor")
            multi_fn = shard_map(
                multi_fn, mesh=self.mesh,
                in_specs=(list(self._tp_param_specs), rep, rep, rep,
                          rep, rep, pool, pool, pool, pool, rep),
                out_specs=(rep, pool, pool, pool, pool),
                check_vma=False)
        return jax.jit(multi_fn, donate_argnums=(6, 7, 8, 9))

    def multi_step(self, cache: PagedKVCache, seq_ids, tokens_np,
                   positions_np, n_steps: int) -> np.ndarray:
        """Decode ``n_steps`` GREEDY tokens for every sequence in ONE
        compiled program.  tokens_np (batch,) int32 — the last sampled
        token per row; positions_np (batch,) int32 — each row's current
        length.  Pages for all n_steps are reserved up front; returns
        (batch, n_steps) int32 of generated tokens."""
        b = len(seq_ids)
        if int(positions_np.max()) + n_steps > self.max_position:
            raise ValueError(
                f"decode through position "
                f"{int(positions_np.max()) + n_steps} exceeds "
                f"max_position_embeddings ({self.max_position})")
        if self._jitted_multi is None:
            self._jitted_multi = self._build_multi()
        before = [cache.length(sid) for sid in seq_ids]
        # all-or-nothing: a mid-batch exhaustion must not leave earlier
        # rows hoarding a chunk's worth of pages the fallback then starves on
        cache.allocate_batch_atomic(seq_ids, n_steps)
        pg, sl = cache.plan_write(seq_ids, n_steps)
        cache.advance(seq_ids, n_steps)
        # per-step (pg, sl): plan_write is row-major (batch, n)
        pg_steps = pg.reshape(b, n_steps).T.copy()       # (n, b)
        sl_steps = sl.reshape(b, n_steps).T.copy()
        pos_steps = (positions_np[None, :]
                     + np.arange(n_steps, dtype=np.int32)[:, None])
        # table covers the FINAL length (pages reserved above); per-step
        # attention masks by lens = pos + 1, so later slots stay unseen
        needed = max(len(cache._seq_pages.get(s, ())) for s in seq_ids)
        tabs, _ = cache.page_table(
            seq_ids, max_pages=max(next_pow2(needed),
                                   self.min_table_pages))
        try:
            _maybe_lose_buffers(cache, seq_ids)
            toks, *pools = self._jitted_multi(
                self._param_arrays(),
                jnp.asarray(tokens_np.astype(np.int32)),
                jnp.asarray(pg_steps), jnp.asarray(sl_steps),
                jnp.asarray(pos_steps), tabs,
                *self._pool_args(cache), self._wscale_args())
        except BaseException:
            # same contract as step()/verify(): rebuild the donated
            # pools only if they were actually consumed, and roll the
            # lengths back so the exact chunk can be replayed — a
            # host-side fault must not zero batchmates' KV (the engine's
            # speculative draft cache rides on this)
            self._recover_pools(cache)
            self._rollback_lengths(cache, seq_ids, before)
            raise
        self._store_pools(cache, *pools)
        return np.asarray(toks).T                        # (batch, n)

    def step(self, cache: PagedKVCache, seq_ids, tokens_np,
             positions_np, sampling=None) -> np.ndarray:
        """One decode token for every sequence.  tokens_np (batch, 1)
        int32; positions_np (batch,) int32 — each row's current length.
        Allocates+advances cache bookkeeping host-side, runs the
        compiled step, writes the updated pools back.  Returns the last
        logits (batch, vocab) float32 numpy — or, with
        ``sampling=(seeds, ctrs, temps, flags)``, the next token ids
        (batch,) int32 sampled INSIDE the compiled step, so only 4
        bytes/row cross the host boundary instead of the full vocab row
        (the logits path stays as the parity/debug escape hatch)."""
        if int(positions_np.max()) + 1 > self.max_position:
            raise ValueError(
                f"decode position {int(positions_np.max()) + 1} exceeds "
                f"max_position_embeddings ({self.max_position})")
        before = [cache.length(sid) for sid in seq_ids]
        for sid in seq_ids:
            cache.allocate(sid, 1)
        pg, sl = cache.plan_write(seq_ids, 1)
        cache.advance(seq_ids, 1)
        # bucket the page-table width to a power of two: an exact width
        # would change shape every time the longest sequence crosses a
        # page boundary, recompiling the whole decode program mid-serving
        # (min_table_pages pins the floor for fully stable signatures)
        needed = max(len(cache._seq_pages.get(s, ())) for s in seq_ids)
        tabs, lens = cache.page_table(
            seq_ids, max_pages=max(next_pow2(needed),
                                   self.min_table_pages))
        sample, s_args = self._sampling_args(sampling)
        try:
            _maybe_lose_buffers(cache, seq_ids)
            out, *pools = self._program("decode", sample)(
                self._param_arrays(),
                jnp.asarray(tokens_np), jnp.asarray(positions_np),
                jnp.asarray(pg), jnp.asarray(sl), lens, tabs, s_args,
                *self._pool_args(cache), self._wscale_args())
        except BaseException:
            # the pools were DONATED: after a mid-step failure (e.g.
            # device OOM) they may be invalidated — rebuild them so the
            # cache object stays usable, and roll the lengths back so
            # the engine's retry/bisect can replay the exact step
            # (sequence KV content is lost only if the program actually
            # ran; a pre-dispatch failure leaves it intact)
            self._recover_pools(cache)
            self._rollback_lengths(cache, seq_ids, before)
            raise
        self._store_pools(cache, *pools)
        return np.asarray(out)


def sample_token(logits_row, do_sample, temperature, rng) -> int:
    """One row's next token: greedy argmax or temperature sampling —
    the single sampling definition shared by PagedGenerator and the
    continuous-batching engine."""
    if do_sample:
        z = np.asarray(logits_row, np.float32) / max(temperature, 1e-6)
        p = np.exp(z - z.max())
        p /= p.sum()
        return int(rng.choice(p.shape[-1], p=p))
    return int(np.asarray(logits_row).argmax())


class PagedGenerator:
    """Batched greedy/sampled decoding over a shared page pool.

    Usage::

        gen = PagedGenerator(model, total_pages=512, page_size=16)
        out_ids = gen.generate(input_ids, max_new_tokens=64)
    """

    def __init__(self, model, total_pages: int = 256, page_size: int = 16,
                 quantize: Optional[str] = None,
                 kv_dtype: Optional[str] = None):
        self.model = model
        self._next_seq = 0
        self.cache = PagedKVCache.from_model(
            model, total_pages=total_pages, page_size=page_size,
            kv_dtype=kv_dtype)
        self._decoder = JittedPagedDecoder(model, quantize=quantize)
        # per-phase wall times of the last generate() call, so callers
        # (bench, schedulers) can split prefill from steady-state decode
        # without a second subtraction run
        self.last_prefill_seconds = 0.0
        self.last_decode_seconds = 0.0

    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None,
                 do_sample: bool = False, temperature: float = 1.0,
                 seed: int = 0):
        """Returns (batch, prompt + generated) token ids (numpy)."""
        ids = np.asarray(input_ids._data if isinstance(input_ids, Tensor)
                         else input_ids)
        b, s = ids.shape
        seq_ids = list(range(self._next_seq, self._next_seq + b))
        self._next_seq += b
        rng = np.random.default_rng(seed)

        try:
            return self._generate(ids, seq_ids, max_new_tokens,
                                  eos_token_id, do_sample, temperature, rng)
        finally:
            # an exception mid-generation (e.g. page-pool exhaustion)
            # must not leak the batch's pages
            for sid in seq_ids:
                self.cache.free(sid)

    def _generate(self, ids, seq_ids, max_new_tokens, eos_token_id,
                  do_sample, temperature, rng):
        import time as _time

        b, s = ids.shape
        with no_grad():
            t0 = _time.perf_counter()
            # ONE compiled prefill program (keyed by prompt length)
            step = self._decoder.prefill(self.cache, seq_ids,
                                         ids.astype(np.int32))
            self.last_prefill_seconds = _time.perf_counter() - t0
            t0 = _time.perf_counter()

            out = [ids]
            if (not do_sample and max_new_tokens > 1
                    and s + max_new_tokens <= self._decoder.max_position):
                # greedy fast path: ALL remaining tokens decode inside
                # ONE compiled lax.scan program (one host dispatch per
                # generation instead of one per token).  eos semantics
                # are applied post-hoc: everything after a row's first
                # eos becomes eos — same output as the stepwise path
                # (whose cache also keeps writing after finish).
                first = np.asarray(step).argmax(axis=-1).astype(np.int32)
                pieces = [first[:, None]]
                cur, pos, remaining = first, s, max_new_tokens - 1
                done = (first == eos_token_id) if eos_token_id is not None \
                    else None
                # power-of-two chunks (rounded UP, extra truncated) so any
                # max_new_tokens reuses a bounded set of compiled scan
                # programs; the round-up must stay inside the rope table.
                # A chunk reservation hitting pool pressure (atomic, rolled
                # back) drops to the per-token continuation below, which
                # decodes from the exact (cur, pos) the chunks reached and
                # can still finish early on eos.
                while remaining > 0:
                    if done is not None and done.all():
                        break           # every row has emitted eos
                    n = min(next_pow2(remaining), 64,
                            self._decoder.max_position - pos)
                    try:
                        chunk = self._decoder.multi_step(
                            self.cache, seq_ids, cur,
                            np.full(b, pos, np.int32), n)
                    except RuntimeError as e:
                        if "out of pages" not in str(e):
                            raise   # device failure: lengths rolled
                            # back, but the chunk's KV content is gone
                        break       # pool pressure: per-token continuation
                    pieces.append(chunk[:, :remaining])
                    if done is not None:
                        done |= (pieces[-1] == eos_token_id).any(axis=1)
                    cur = chunk[:, -1].astype(np.int32)
                    pos += n
                    remaining -= n
                while remaining > 0:
                    if done is not None and done.all():
                        break
                    logits = self._decoder.step(
                        self.cache, seq_ids, cur[:, None].astype(np.int32),
                        np.full(b, pos, np.int32))
                    cur = logits.argmax(axis=-1).astype(np.int32)
                    pieces.append(cur[:, None])
                    if done is not None:
                        done |= cur == eos_token_id
                    pos += 1
                    remaining -= 1
                gen = np.concatenate(pieces, axis=1)
                if eos_token_id is not None:
                    hit = gen == eos_token_id
                    after = (np.cumsum(hit, axis=1) - hit.astype(int)) > 0
                    gen = np.where(after, eos_token_id, gen)
                    # stepwise width contract: stop at the step where the
                    # LAST row finished
                    alldone = (np.cumsum(hit, axis=1) > 0).all(axis=0)
                    if alldone.any():
                        gen = gen[:, :int(np.argmax(alldone)) + 1]
                out.append(gen.astype(ids.dtype))
                self.last_decode_seconds = _time.perf_counter() - t0
                return np.concatenate(out, axis=1)

            finished = np.zeros(b, bool)
            pos = s
            for _ in range(max_new_tokens):
                nxt = np.array([
                    sample_token(row, do_sample, temperature, rng)
                    for row in step])
                if eos_token_id is not None:
                    nxt = np.where(finished, eos_token_id, nxt)
                    finished |= nxt == eos_token_id
                out.append(nxt[:, None].astype(ids.dtype))
                if eos_token_id is not None and finished.all():
                    break
                # ONE compiled program per decode token (embed + all
                # layers + logits), pools donated through the step
                step = self._decoder.step(
                    self.cache, seq_ids,
                    out[-1].astype(np.int32),
                    np.full(b, pos, np.int32))
                pos += 1
            self.last_decode_seconds = _time.perf_counter() - t0

        return np.concatenate(out, axis=1)
