"""Heterogeneous-workload scheduler (ISSUE 7).

Admission policy + per-class SLO accounting for the continuous-batching
engine.  The engine was FIFO with one implicit tenant: a single long
prompt stalled every interactive decode step behind a monolithic
prefill.  This module supplies the three scheduling pillars the engine
delegates to:

  * **priority classes** — requests carry a class
    (``interactive`` > ``standard`` > ``batch`` by default); classes
    have weights (admission share) and a ``preemptible`` flag (the
    engine may pause a preemptible request's CHUNKED prefill to hand
    its slot to more urgent traffic — the paused request keeps its
    pages and resumes, it never re-prefills);
  * **weighted-fair queueing** — admission order is deficit-round-robin
    at two levels: across classes (deficit replenished by class
    weight, cost charged in reserved pages, highest accumulated
    deficit served first so long-run service share tracks the weights
    while no class starves) and, within a class, across per-tenant
    FIFO queues (equal-quantum DRR, so one tenant's burst cannot
    monopolize its class);
  * **bounded per-class queues** — each class has its own admission
    queue bound; overflow raises :class:`QueueFull` naming the class,
    which the engine maps to :class:`EngineSaturated` and the HTTP
    server to 429 with a class-aware ``Retry-After`` (derived from the
    *requesting class's* backlog, not the global queue).

Concurrency contract: a ``WorkloadScheduler`` owns NO lock — every
method is called with the engine's ``_cond`` held (the same discipline
tpu_lint TPL004 enforces on the engine's own state).  All mutation
happens on the engine scheduler thread or under that lock.

SLO observability: per-class histograms (queue wait, TTFT, TPOT) and
counters (admissions, rejections, preemptions, prefill chunks,
deferrals) land in the process-wide monitor registry, labeled
``cls=<class>``, surfaced via ``/metrics`` and summarized in
``/health``.
"""
from __future__ import annotations

import time
from collections import OrderedDict, deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from .. import monitor

__all__ = [
    "PriorityClass", "WorkloadScheduler", "QueueFull",
    "DEFAULT_CLASSES", "DEFAULT_CLASS",
]


@dataclass(frozen=True)
class PriorityClass:
    """One scheduling class.  ``rank`` orders urgency (lower = more
    urgent: chunk budget and slot preemption both favor lower ranks);
    ``weight`` is the class's admission share under weighted DRR;
    ``preemptible`` marks classes whose chunked prefill — and, since
    ISSUE 19, whose in-flight decode — the engine may pause for
    lower-rank traffic; ``max_queue`` overrides the scheduler-wide
    per-class queue bound.

    SLO budgets (ISSUE 19, both optional — None disables the control
    loop for the class): ``deadline_s`` is the class's queue-wait/TTFT
    budget — admission sheds a request on arrival when the projected
    queue wait (class depth x measured decode-step p50) already
    exceeds it, and TTFT <= deadline_s is what the per-class SLO
    attainment window counts; ``tpot_budget_s`` is the per-token decode
    budget — when a running row of this class sees the engine's
    measured step time exceed it at full occupancy, the engine pauses
    the least-urgent preemptible *decoding* row to shrink the batch."""

    name: str
    rank: int
    weight: int = 1
    preemptible: bool = False
    max_queue: Optional[int] = None
    deadline_s: Optional[float] = None
    tpot_budget_s: Optional[float] = None


#: the default class taxonomy: chat-style traffic outranks everything,
#: offline/batch work is preemptible and gets the smallest share
DEFAULT_CLASSES: Tuple[PriorityClass, ...] = (
    PriorityClass("interactive", rank=0, weight=8),
    PriorityClass("standard", rank=1, weight=4),
    PriorityClass("batch", rank=2, weight=1, preemptible=True),
)
DEFAULT_CLASS = "standard"

#: deficit accumulation cap, in quanta: an idle-then-bursty class may
#: bank at most this many rounds of credit (classic DRR zeroes credit
#: on empty; the cap bounds it instead so a re-appearing class cannot
#: monopolize admission with stale credit)
_DEFICIT_CAP_ROUNDS = 16

# per-class SLO telemetry (ISSUE 7): the scenario-matrix lane and the
# /metrics surface read exactly these series
_queue_wait_s = monitor.histogram(
    "sched_queue_wait_seconds", "submit -> admission, per class",
    ("cls",))
_ttft_s = monitor.histogram(
    "sched_ttft_seconds", "submit -> first sampled token, per class",
    ("cls",))
_tpot_s = monitor.histogram(
    "sched_tpot_seconds", "mean seconds per output token after the "
    "first, observed at retirement, per class", ("cls",))
_queue_depth_g = monitor.gauge(
    "sched_queue_depth", "requests waiting for admission, per class",
    ("cls",))
_admitted_total = monitor.counter(
    "sched_admitted_total", "requests admitted, per class", ("cls",))
_rejected_total = monitor.counter(
    "sched_rejected_total", "submissions rejected by the class's "
    "bounded queue, per class", ("cls",))
_preempted_total = monitor.counter(
    "sched_preemptions_total", "preemptible prefills paused so a more "
    "urgent class could take the slot, per (preempted) class", ("cls",))
_resumed_total = monitor.counter(
    "sched_resumed_total", "preempted prefills resumed (pages kept, "
    "never re-prefilled), per class", ("cls",))
_chunks_total = monitor.counter(
    "sched_prefill_chunks_total", "prefill chunks executed, per class",
    ("cls",))
_deferrals_total = monitor.counter(
    "sched_chunk_deferrals_total", "prefill chunks deferred because a "
    "step's chunk budget went to more urgent classes, per class",
    ("cls",))
_preempt_expired_total = monitor.counter(
    "sched_preempt_expired_total", "preempted prefills reaped because "
    "they held their page reservation past the resume TTL without a "
    "slot freeing up (ISSUE 8: the reservation bound), per class",
    ("cls",))
_shed_total = monitor.counter(
    "sched_shed_on_arrival_total", "submissions shed at admission by "
    "the overload controller (ISSUE 19): the class's deadline budget "
    "was already blown by the projected queue wait, or the brownout "
    "ladder sheds the class outright — rejected in microseconds with "
    "a truthful Retry-After instead of timing out holding pages, per "
    "class", ("cls",))

#: recent per-class SLO attainment window (requests): big enough to
#: smooth one burst, small enough that recovery is visible within a
#: bench measurement window
_ATTAINMENT_WINDOW = 64


class QueueFull(RuntimeError):
    """A class's bounded admission queue overflowed.  The engine maps
    this to :class:`EngineSaturated`; ``priority_class`` names the
    class whose backlog the 429 ``Retry-After`` must be derived from."""

    def __init__(self, priority_class: str, depth: int, bound: int):
        super().__init__(
            f"admission queue for class {priority_class!r} is full "
            f"({depth}/{bound} requests); retry later")
        self.priority_class = priority_class
        self.depth = depth
        self.bound = bound


class _TenantQueue:
    __slots__ = ("queue", "deficit")

    def __init__(self):
        self.queue: Deque = deque()
        self.deficit = 0.0


class _ClassState:
    __slots__ = ("spec", "tenants", "deficit", "depth", "slo_recent")

    def __init__(self, spec: PriorityClass):
        self.spec = spec
        # insertion-ordered so tenant DRR visits are deterministic
        self.tenants: "OrderedDict[str, _TenantQueue]" = OrderedDict()
        self.deficit = 0.0
        self.depth = 0
        # sliding window of per-request SLO outcomes (ISSUE 19): 1 =
        # TTFT met the class deadline budget, 0 = blown
        self.slo_recent: Deque[int] = deque(maxlen=_ATTAINMENT_WINDOW)


class WorkloadScheduler:
    """Per-class, per-tenant admission queues + weighted-DRR selection.

    NOT thread-safe by itself: the owning engine calls every method
    with its scheduler lock held (see module docstring).
    """

    def __init__(self, classes: Optional[Sequence[PriorityClass]] = None,
                 max_queue: int = 256,
                 default_class: str = DEFAULT_CLASS):
        specs = tuple(classes) if classes is not None else DEFAULT_CLASSES
        if not specs:
            raise ValueError("at least one PriorityClass is required")
        names = [c.name for c in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate class names in {names}")
        self._classes: Dict[str, _ClassState] = {
            c.name: _ClassState(c) for c in specs}
        self._by_rank: List[_ClassState] = sorted(
            self._classes.values(), key=lambda cs: (cs.spec.rank,
                                                    cs.spec.name))
        self.max_queue = int(max_queue)
        if default_class not in self._classes:
            raise ValueError(
                f"default_class {default_class!r} is not one of {names}")
        self.default_class = default_class
        for name in self._classes:
            _queue_depth_g.set(0, cls=name)
            _shed_total.inc(0, cls=name)   # materialize for /metrics

    # ----------------------------------------------------------- lookup
    def resolve(self, name: Optional[str]) -> PriorityClass:
        """The class for a submitted ``priority`` (None -> default).
        ValueError for unknown names — the server maps it to 400: an
        unknown class is the client's mistake, never a retryable."""
        if name is None:
            name = self.default_class
        cs = self._classes.get(name)
        if cs is None:
            raise ValueError(
                f"unknown priority class {name!r}; classes are "
                f"{sorted(self._classes)}")
        return cs.spec

    def class_of(self, req) -> PriorityClass:
        return self._classes[req.priority].spec

    @property
    def classes(self) -> Tuple[PriorityClass, ...]:
        return tuple(cs.spec for cs in self._by_rank)

    def __len__(self) -> int:
        return sum(cs.depth for cs in self._by_rank)

    def depth(self, priority: Optional[str] = None) -> int:
        """Queued requests in one class (or overall with None)."""
        if priority is None:
            return len(self)
        cs = self._classes.get(priority)
        return 0 if cs is None else cs.depth

    def depths(self) -> Dict[str, int]:
        return {cs.spec.name: cs.depth for cs in self._by_rank}

    def tenant_depths(self) -> Dict[str, Dict[str, int]]:
        return {cs.spec.name: {t: len(tq.queue)
                               for t, tq in cs.tenants.items()
                               if tq.queue}
                for cs in self._by_rank}

    def policy(self) -> dict:
        """JSON-able policy knobs + live depths for ``/health``."""
        return {cs.spec.name: {
            "rank": cs.spec.rank,
            "weight": cs.spec.weight,
            "preemptible": cs.spec.preemptible,
            "max_queue": (self.max_queue if cs.spec.max_queue is None
                          else cs.spec.max_queue),
            "queued": cs.depth,
            "deadline_s": cs.spec.deadline_s,
            "tpot_budget_s": cs.spec.tpot_budget_s,
            "slo_attainment": self.attainment(cs.spec.name),
        } for cs in self._by_rank}

    def attainment(self, priority: str) -> Optional[float]:
        """Fraction of the class's last ``_ATTAINMENT_WINDOW`` retired
        first tokens that met ``deadline_s`` (None while the class has
        no budget or no samples).  Feeds the brownout ladder and the
        fleet autoscaler."""
        cs = self._classes.get(priority)
        if cs is None or not cs.slo_recent:
            return None
        return sum(cs.slo_recent) / len(cs.slo_recent)

    def urgent_attainment(self) -> Optional[float]:
        """Attainment of the most urgent class that carries a deadline
        budget — the brownout ladder's SLO input."""
        for cs in self._by_rank:
            if cs.spec.deadline_s is not None:
                return self.attainment(cs.spec.name)
        return None

    # ------------------------------------------------------------ queues
    def push(self, req) -> None:
        """Enqueue onto the request's (class, tenant) queue.  Raises
        :class:`QueueFull` when the class's bounded queue is full."""
        cs = self._classes[self.resolve(req.priority).name]
        req.priority = cs.spec.name          # normalize None -> default
        bound = (self.max_queue if cs.spec.max_queue is None
                 else cs.spec.max_queue)
        if cs.depth >= bound:
            _rejected_total.inc(cls=cs.spec.name)
            raise QueueFull(cs.spec.name, cs.depth, bound)
        tq = cs.tenants.get(req.tenant)
        if tq is None:
            tq = cs.tenants[req.tenant] = _TenantQueue()
        tq.queue.append(req)
        cs.depth += 1
        _queue_depth_g.set(cs.depth, cls=cs.spec.name)

    def _set_depth(self, cs: _ClassState, delta: int) -> None:
        cs.depth += delta
        _queue_depth_g.set(cs.depth, cls=cs.spec.name)
        if cs.depth == 0:
            # classic DRR: an emptied queue forfeits leftover credit —
            # and its tenant entries go too, so the per-tenant map can
            # never grow without bound on client-supplied tenant ids
            cs.deficit = 0.0
            cs.tenants.clear()

    @staticmethod
    def _prune_tenants(cs: _ClassState) -> None:
        """Drop emptied tenant queues (forfeiting their DRR credit,
        the classic rule) so the tenant map is bounded by the LIVE
        tenant count, not by every tenant string ever submitted."""
        for name in [n for n, tq in cs.tenants.items() if not tq.queue]:
            del cs.tenants[name]

    def min_waiting_rank(self) -> Optional[int]:
        """Rank of the most urgent nonempty class, or None when idle —
        the engine's slot-preemption trigger reads this."""
        for cs in self._by_rank:
            if cs.depth:
                return cs.spec.rank
        return None

    def peek_urgent(self):
        """A head request of the most urgent nonempty class (first
        nonempty tenant queue), without popping — the engine uses it
        for a pages-fit check before paying for a slot preemption."""
        for cs in self._by_rank:
            if not cs.depth:
                continue
            for tq in cs.tenants.values():
                if tq.queue:
                    return tq.queue[0]
        return None

    def pending(self) -> List:
        """Every queued request WITHOUT popping, most urgent class
        first (FIFO within each tenant queue) — the engine's
        ``snapshot()`` serializes these alongside the in-flight lists
        (ISSUE 8)."""
        out: List = []
        for cs in self._by_rank:
            for tq in cs.tenants.values():
                out.extend(tq.queue)
        return out

    def pop_all(self) -> List:
        """Remove and return every queued request (drain-reject /
        fail-all paths)."""
        out: List = []
        for cs in self._by_rank:
            for tq in cs.tenants.values():
                out.extend(tq.queue)
                tq.queue.clear()
            if cs.depth:
                self._set_depth(cs, -cs.depth)
        return out

    def reap(self, now: float) -> List:
        """Remove queued requests whose lifecycle ended (cancel /
        deadline) and return them — the engine counts and wakes them."""
        out: List = []
        for cs in self._by_rank:
            removed = 0
            for tq in cs.tenants.values():
                if not tq.queue:
                    continue
                keep: Deque = deque()
                for r in tq.queue:
                    if r._lifecycle_error(now, queued=True) is None:
                        keep.append(r)
                    else:
                        out.append(r)
                        removed += 1
                tq.queue = keep
            if removed:
                self._prune_tenants(cs)
                self._set_depth(cs, -removed)
        return out

    # --------------------------------------------------------- selection
    def _tenant_candidate(self, cs: _ClassState, can_admit):
        """(tenant, tenant_queue, req, cost) for this class under
        tenant-level DRR: among tenants whose HEAD fits right now,
        serve the highest deficit (replenishing equal quanta until
        someone affords).  Heads are never skipped within a tenant
        queue — FIFO per tenant is part of the fairness contract."""
        heads = []
        for tname, tq in cs.tenants.items():
            if not tq.queue:
                continue
            cost = can_admit(tq.queue[0])
            if cost is not None:
                heads.append((tname, tq, tq.queue[0], float(cost)))
        if not heads:
            return None
        # equal replenish quantum per tenant (weights are a CLASS
        # concept); the service charge below is what makes shares fair
        quantum = max(1.0, min(h[3] for h in heads))
        cap = _DEFICIT_CAP_ROUNDS * max(h[3] for h in heads)
        while True:
            afford = [h for h in heads if h[1].deficit >= h[3]]
            if afford:
                return max(afford, key=lambda h: h[1].deficit)
            for _, tq, _, _ in heads:
                tq.deficit = min(tq.deficit + quantum, cap)

    def pop_next(self, can_admit: Callable,
                 max_rank: Optional[int] = None) -> Optional[object]:
        """Pop the next request to admit, or None if nothing admissible.

        ``can_admit(req) -> Optional[cost]`` must be PURE: it returns
        the admission cost (reserved pages) when the request fits the
        engine's capacity right now, else None.  Selection is weighted
        DRR across classes (deficit += weight per replenish round;
        highest-deficit affordable class served, rank breaking ties so
        urgency wins among equals), then tenant DRR within the class.
        Deficits are charged in cost units, so service share tracks
        weight x pages, not request count.

        ``max_rank`` restricts candidates to classes at that rank or
        more urgent — the engine passes the rank it just PREEMPTED a
        victim for, so a slot paid for with a preemption can never be
        consumed by a less urgent class's banked deficit."""
        candidates = []
        for cs in self._by_rank:
            if not cs.depth:
                continue
            if max_rank is not None and cs.spec.rank > max_rank:
                continue
            found = self._tenant_candidate(cs, can_admit)
            if found is not None:
                candidates.append((cs,) + found)
        if not candidates:
            return None
        # the cap banks at most _DEFICIT_CAP_ROUNDS rounds of weight,
        # but must still reach the costliest head: costs are PAGES,
        # weights are quanta — a lone low-weight class with a large
        # request must become affordable, not spin the loop forever
        cap = max(_DEFICIT_CAP_ROUNDS
                  * max(c[0].spec.weight for c in candidates),
                  max(c[4] for c in candidates))
        while True:
            afford = [c for c in candidates if c[0].deficit >= c[4]]
            if afford:
                cs, tname, tq, req, cost = min(
                    afford, key=lambda c: (-c[0].deficit, c[0].spec.rank))
                break
            for cs, _, _, _, _ in candidates:
                cs.deficit = min(cs.deficit + cs.spec.weight, cap)
        popped = tq.queue.popleft()
        assert popped is req
        cs.deficit -= cost
        tq.deficit -= cost
        self._prune_tenants(cs)
        self._set_depth(cs, -1)
        return req

    # ------------------------------------------------------ SLO accounting
    def note_admitted(self, req, now: Optional[float] = None) -> None:
        now = time.perf_counter() if now is None else now
        _admitted_total.inc(cls=req.priority)
        _queue_wait_s.observe(max(0.0, now - req.submitted_at),
                              cls=req.priority)

    def note_first_token(self, req, ttft_s: float) -> None:
        _ttft_s.observe(ttft_s, cls=req.priority)
        cs = self._classes[req.priority]
        if cs.spec.deadline_s is not None:
            cs.slo_recent.append(1 if ttft_s <= cs.spec.deadline_s
                                 else 0)

    def note_shed(self, priority: str) -> None:
        """One arrival shed by the overload controller (ISSUE 19).
        Sheds do NOT enter the attainment window: attainment is defined
        over ADMITTED work (a shed is an honest sub-millisecond 429,
        not a blown promise), and counting them would let rung-3
        interactive shedding depress the very signal whose recovery
        de-escalates the ladder."""
        _shed_total.inc(cls=priority)

    def note_retired(self, req) -> None:
        """Observe TPOT at retirement: mean seconds per output token
        after the first (decode steady-state latency, the SLO
        complement of TTFT)."""
        if req.error is not None or req.first_token_at is None \
                or req.finished_at is None:
            return
        n = len(req.generated)
        if n > 1:
            _tpot_s.observe(
                (req.finished_at - req.first_token_at) / (n - 1),
                cls=req.priority)

    def note_preempted(self, req) -> None:
        _preempted_total.inc(cls=req.priority)

    def note_resumed(self, req) -> None:
        _resumed_total.inc(cls=req.priority)

    def note_chunk(self, req) -> None:
        _chunks_total.inc(cls=req.priority)

    def note_chunk_deferred(self, req) -> None:
        _deferrals_total.inc(cls=req.priority)

    def note_preempt_expired(self, req) -> None:
        _preempt_expired_total.inc(cls=req.priority)
