"""HTTP model server over the Predictor (reference: the C++ fluid
inference server / Paddle Serving's role — here a dependency-free
stdlib implementation fronting the StableHLO Predictor).

Endpoints (JSON; arrays as nested lists with dtype strings):
  GET  /health          -> {"status": "ok", "model": prefix,
                            "uptime_s": ..., "requests_total": ...}
  GET  /metadata        -> input/output names
  GET  /metrics         -> Prometheus text exposition (paddle_tpu.monitor)
  POST /predict         -> {"inputs": {name: {"data": [...], "dtype": ...,
                            "shape": [...]}}} -> {"outputs": {...}}

A PredictorPool serves concurrent requests; the ThreadingHTTPServer
dispatches each request to a pool slot.  Every request is measured into
the process-wide metrics registry (``requests_total`` counter,
``request_latency_seconds`` histogram, tagged by server and route).
"""
from __future__ import annotations

import json
import os
import threading
import time
import warnings
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from .. import monitor
from . import Config, Predictor, PredictorPool

__all__ = ["InferenceServer", "GenerationServer", "serve"]


_requests_total = monitor.counter(
    "requests_total", "HTTP requests served", ("server", "route"))
_request_latency = monitor.histogram(
    "request_latency_seconds", "HTTP request wall latency",
    ("server", "route"))


class _JsonHandler(BaseHTTPRequestHandler):
    """Shared HTTP plumbing: quiet logs (opt-in via access_log=True) +
    JSON replies + per-route telemetry."""

    server_kind = "http"     # overridden per server class

    def log_message(self, fmt, *args):
        if getattr(self, "_outer", None) is not None \
                and self._outer._access_log:
            BaseHTTPRequestHandler.log_message(self, fmt, *args)

    def _reply(self, code, payload, headers=None):
        body = json.dumps(payload).encode()
        self._reply_bytes(code, body, "application/json", headers)

    def _reply_text(self, code, text,
                    content_type="text/plain; version=0.0.4"):
        self._reply_bytes(code, text.encode(), content_type)

    def _reply_bytes(self, code, body, content_type, headers=None):
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _read_json(self):
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n))

    def _track(self, route):
        """Count the request (registry + per-server cumulative count)
        and return a latency span for the handling block."""
        _requests_total.inc(server=self.server_kind, route=route)
        self._outer._bump_requests()
        return monitor.span(f"http/{self.server_kind}{route}",
                            histogram=_request_latency,
                            server=self.server_kind, route=route)


class _ServerLifecycle:
    """start/stop/context-manager + uptime/request accounting shared by
    both servers."""

    def _init_stats(self, access_log: bool):
        self._access_log = bool(access_log)
        self._started_at = time.monotonic()
        self._requests_lock = threading.Lock()
        self._requests_served = 0
        # readiness (ISSUE 14 satellite): set once serve_forever is
        # live — a supervisor starting replicas on port 0 waits on
        # this instead of sleep-and-polling the socket.  The listener
        # is BOUND at construction (``port`` is final then, even for
        # an ephemeral port-0 bind, and any journal/snapshot restore
        # has completed), so connections made after wait_ready() are
        # served, never refused.
        self._ready = threading.Event()

    @property
    def address(self):
        """``(host, port)`` of the bound listener — final at
        construction, port-0 binds resolved to the ephemeral port."""
        return (self.host, self.port)

    def wait_ready(self, timeout=None) -> bool:
        """Block until :meth:`start`'s serving thread is live (True),
        or ``timeout`` elapsed (False)."""
        return self._ready.wait(timeout)

    def _bump_requests(self):
        with self._requests_lock:
            self._requests_served += 1

    @property
    def requests_served(self) -> int:
        with self._requests_lock:
            return self._requests_served

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._started_at

    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        self._ready.set()
        return self

    def stop(self):
        self._ready.clear()
        if self._thread is not None:
            # shutdown() handshakes with the serve_forever loop — on a
            # never-started server it would wait forever, so only the
            # socket close applies there
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


class InferenceServer(_ServerLifecycle):
    """Serve a jit.save artifact over HTTP.

    Usage::

        server = InferenceServer("ckpt/model", device="cpu", pool_size=2)
        server.start()              # non-blocking; .port has the port
        ...
        server.stop()
    """

    def __init__(self, model_prefix: str, host: str = "127.0.0.1",
                 port: int = 0, pool_size: int = 1, device: str = "",
                 access_log: bool = False):
        config = Config(model_prefix)
        if device == "cpu":
            config.disable_gpu()
        elif device not in ("", "tpu", "gpu"):
            raise ValueError(
                f"device must be '', 'cpu', 'tpu' or 'gpu', got {device!r}")
        self._prefix = model_prefix
        self._pool = PredictorPool(config, pool_size)
        self._pool_lock = threading.Lock()
        self._next = [0]
        self._size = pool_size
        self._init_stats(access_log)
        outer = self

        class Handler(_JsonHandler):
            server_kind = "inference"
            _outer = outer

            def do_GET(self):
                if self.path == "/health":
                    with self._track("/health"):
                        self._reply(200, {
                            "status": "ok", "model": outer._prefix,
                            "uptime_s": round(outer.uptime_s, 3),
                            "requests_total": outer.requests_served})
                elif self.path == "/metadata":
                    with self._track("/metadata"):
                        p = outer._pool.retrieve(0)
                        self._reply(200, {
                            "inputs": p.get_input_names(),
                            "outputs": p.get_output_names()})
                elif self.path == "/metrics":
                    with self._track("/metrics"):
                        self._reply_text(200, monitor.prometheus_text())
                elif self.path == "/debug/trace":
                    with self._track("/debug/trace"):
                        self._reply(200, monitor.export_chrome_trace())
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                with self._track("/predict"):
                    try:
                        out = outer._predict(self._read_json())
                        self._reply(200, out)
                    except Exception as e:   # noqa: BLE001
                        self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def _predict(self, req):
        inputs = req.get("inputs", {})
        with self._pool_lock:
            idx = self._next[0] % self._size
            self._next[0] += 1
        pred = self._pool.retrieve(idx)
        names = pred.get_input_names()
        missing = [n for n in names if n not in inputs]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        arrays = []
        for name in names:
            spec = inputs[name]
            arr = np.asarray(spec["data"],
                             dtype=spec.get("dtype", "float32"))
            if "shape" in spec:
                arr = arr.reshape(spec["shape"])
            arrays.append(arr)
        # handle-free run: inputs are passed per call and outputs returned
        # directly, so concurrent requests sharing a pool slot never race
        # through the copy_from_cpu/run/copy_to_cpu handle state
        results = pred.run(arrays)
        outputs = {}
        for name, out in zip(pred.get_output_names(), results):
            a = np.asarray(out)
            outputs[name] = {"data": a.tolist(), "dtype": str(a.dtype),
                             "shape": list(a.shape)}
        return {"outputs": outputs}


class GenerationServer(_ServerLifecycle):
    """Serve a causal LM's paged-KV decode path over HTTP (the serving
    role of the reference's block_multihead_attention deployment stack).

    POST /generate  {"input_ids": [[...], ...], "max_new_tokens": N,
                     "eos_token_id": id?, "do_sample": bool?,
                     "temperature": float?, "draft": bool?}
        -> {"output_ids": [[...], ...], "new_tokens": N}

    Requests are CONTINUOUSLY BATCHED: every row of every in-flight HTTP
    request is its own sequence in one shared ContinuousBatchingEngine —
    concurrent requests decode together per step instead of queueing
    behind a server lock, and short generations retire without waiting
    for long ones.  Sampled requests draw a fresh per-request seed
    unless the request pins one.  The engine's hot-path knobs plumb
    through: ``sample_on_device`` (fused in-step sampling) and
    ``prefix_cache`` (shared-prompt-prefix KV reuse) — both on by
    default; so do the resilience knobs ``max_queue`` /
    ``default_ttl_s`` / ``step_timeout_s`` (ISSUE 4), and a request
    body may set ``timeout_s`` as its own total TTL.

    Speculative decoding (ISSUE 6): construct with ``draft_model`` and
    greedy requests decode speculatively (``spec_tokens`` draft
    proposals per step, bit-exact vs target-only greedy); a request
    body may set ``"draft": false`` to opt out, or ``true`` to demand
    it (400 if the server has no draft model).  ``/health`` reports
    the draft pool; acceptance counters land in ``/metrics``
    (``spec_*`` series).

    Error mapping (the resilience HTTP contract):
      400 = malformed request (bad JSON/shape, or prompt +
            max_new_tokens past the model's rope table);
      429 = admission queue full (``EngineSaturated``) — retry after
            the ``Retry-After`` header;
      503 = pool/capacity exhaustion or draining (retry elsewhere);
      504 = the request's deadline (TTL / queue-wait) expired;
      500 = unexpected server fault.

    Graceful drain: ``begin_drain()`` (or SIGTERM via
    ``attach_preemption``) stops new admissions — fresh /generate
    requests get 503 with ``"draining": true`` while in-flight
    generations run to completion; /health reports the drain state.

    Scheduling & multi-tenancy (ISSUE 7): a request body may set
    ``"priority"`` (scheduling class: ``interactive`` / ``standard`` /
    ``batch`` by default; unknown -> 400) and ``"tenant"`` (fair-queued
    within the class).  ``prefill_chunk_tokens`` caps per-step prefill
    so long prompts interleave with decode instead of stalling it;
    ``min_table_pages`` pins the compiled programs' page-table width
    for recompile-free mixed-length serving.  429 responses carry a
    class-aware ``Retry-After``; ``/health`` reports per-class queue
    depths and the active policy knobs under ``"scheduler"``.

    Quantized serving (ISSUE 9): ``quantize="w8"|"w8a8"`` runs the
    compiled decode/prefill/chunk/verify programs with int8 weights
    (scales traced, calibrated through the PTQ observers);
    ``kv_quant="int8"`` stores KV pages int8 with fused
    quantize-on-append / dequant-in-kernel — roughly 4x (f32) or 2x
    (bf16) the concurrent sequences per pool byte.  ``/health``
    reports both modes plus resident KV byte accounting.

    Crash consistency (ISSUE 8): with ``snapshot_path`` set, SIGTERM
    (via ``attach_preemption``) first journals every in-flight request
    — ``engine.snapshot()`` written atomically to the path — and THEN
    begins the graceful drain; a restarted server finding the journal
    consumes it (renamed to ``<path>.restored`` so a crash loop cannot
    double-resume) and resubmits each request through the engine's
    replay primitive, so mid-stream generations continue bit-exactly
    in the new process.  ``save_snapshot()`` is also callable directly
    (an operator checkpoint before risky maintenance).  ``/health``
    reports ``snapshot_path`` and the restored-request count when the
    knob is set.

    SIGKILL-grade durability (ISSUE 13): ``journal_dir`` supersedes
    the cooperative snapshot with a WRITE-AHEAD request journal —
    every admission/step/retirement is CRC-framed to disk as it
    happens (``journal_fsync``: ``always`` / ``interval_ms`` / ``os``),
    so a ``kill -9``, OOM-kill or power loss mid-decode loses nothing:
    the restarted server scans the segments, reconstructs the live set
    (admitted minus retired, journal deadlines verbatim) and resumes
    every request bit-exactly before the listener opens, with
    ``/result/<request_id>`` re-attaching across the hard restart
    exactly as it does across SIGTERM.  The SIGTERM path collapses
    onto the same format: the pre-drain "snapshot" is just
    ``journal.flush(sync=True)`` (the WAL already holds everything)
    and the post-drain refresh a final compaction.  ``/health``
    reports the journal path, segment count and fsync policy;
    ``journal_dir`` and ``snapshot_path`` are mutually exclusive.

    Observability (ISSUE 10): a request body may pin ``"request_id"``
    (multi-row bodies get ``<id>/<row>`` per row); the reply always
    carries ``"request_ids"``, and ``GET /result/<id>`` re-attaches to
    a finished (200) or in-flight (202) generation — including after a
    snapshot/restore restart, where journaled ids are preserved.
    ``POST /debug/trace/start`` / ``/debug/trace/stop`` bracket a
    capture window; ``GET /debug/trace`` exports it as chrome-trace
    JSON (engine-step track + per-request flow events + profiler host
    spans) and ``GET /debug/requests/<id>`` returns one request's raw
    event timeline.  ``GET /debug/cost`` runs the analytical cost model
    over the decode program and publishes ``program_flops_total`` /
    ``program_hbm_bytes`` / ``mfu`` to ``/metrics``; its ``spmd``
    group (ISSUE 11) adds the tier-3 distributed audit — static peak
    HBM, priced collective bytes and analytic ICI seconds, sharding
    hazard count — publishing ``program_peak_hbm_bytes`` /
    ``collective_bytes_total`` / ``ici_time_seconds`` alongside.
    """

    def __init__(self, model, host: str = "127.0.0.1", port: int = 0,
                 total_pages: int = 512, page_size: int = 16,
                 max_batch: int = 8, sample_on_device: bool = True,
                 prefix_cache: bool = True, access_log: bool = False,
                 max_queue: int = 256,
                 default_ttl_s: Optional[float] = None,
                 step_timeout_s: Optional[float] = None,
                 draft_model=None, spec_tokens: int = 4,
                 draft_total_pages: Optional[int] = None,
                 prefill_chunk_tokens: Optional[int] = None,
                 scheduler_classes=None,
                 min_table_pages: int = 1,
                 snapshot_path: Optional[str] = None,
                 preempt_resume_ttl_s: Optional[float] = None,
                 quantize: Optional[str] = None,
                 kv_quant: Optional[str] = None,
                 replay_batch: Optional[bool] = None,
                 journal_dir: Optional[str] = None,
                 journal_fsync: str = "interval_ms",
                 journal_fsync_interval_ms: float = 50.0,
                 journal_segment_bytes: int = 1 << 20,
                 journal_fsync_timeout_s: Optional[float] = None,
                 brownout_thresholds=None,
                 brownout_patience: int = 3,
                 decode_preempt: bool = True,
                 tpot_preempt_cooldown_s: float = 0.25,
                 tp: int = 1,
                 tp_quant_collectives: bool = False):
        from .continuous import (ContinuousBatchingEngine,
                                 DeadlineExceeded, EngineDraining,
                                 EngineSaturated)
        from ..testing import faults as _faults

        if journal_dir and snapshot_path:
            raise ValueError(
                "journal_dir and snapshot_path are mutually exclusive: "
                "the write-ahead journal supersedes the cooperative "
                "snapshot (one persistence format, ISSUE 13)")
        self._journal = None
        self._journal_entries = []
        if journal_dir:
            from .journal import RequestJournal
            # constructing the journal RECOVERS a predecessor's
            # segments (crash-loop-safe: the live set is re-compacted
            # into a fresh durable segment before the old ones are
            # consumed) — the entries are resubmitted after the
            # listener socket binds, mirroring the snapshot path
            self._journal = RequestJournal(
                journal_dir, fsync=journal_fsync,
                fsync_interval_ms=journal_fsync_interval_ms,
                segment_bytes=journal_segment_bytes,
                fsync_timeout_s=journal_fsync_timeout_s)
            self._journal_entries = self._journal.recovered_requests()
        try:
            self._engine = ContinuousBatchingEngine(
                model, total_pages=total_pages, page_size=page_size,
                max_batch=max_batch, sample_on_device=sample_on_device,
                prefix_cache=prefix_cache, max_queue=max_queue,
                default_ttl_s=default_ttl_s,
                step_timeout_s=step_timeout_s,
                draft_model=draft_model, spec_tokens=spec_tokens,
                draft_total_pages=draft_total_pages,
                prefill_chunk_tokens=prefill_chunk_tokens,
                scheduler_classes=scheduler_classes,
                min_table_pages=min_table_pages,
                preempt_resume_ttl_s=preempt_resume_ttl_s,
                quantize=quantize, kv_quant=kv_quant,
                replay_batch=replay_batch, journal=self._journal,
                brownout_thresholds=brownout_thresholds,
                brownout_patience=brownout_patience,
                decode_preempt=decode_preempt,
                tpot_preempt_cooldown_s=tpot_preempt_cooldown_s,
                tp=tp, tp_quant_collectives=tp_quant_collectives)
        except BaseException:
            # a rejected engine knob must not leak the journal's
            # writer thread / open segment / watchdog heartbeat (the
            # live set stays on disk for the next attempt)
            if self._journal is not None:
                self._journal.close()
            raise
        self._count_lock = threading.Lock()
        self._request_count = 0
        self._drain_thread: Optional[threading.Thread] = None
        self._drain_result: Optional[bool] = None
        self._snapshot_path = snapshot_path
        self._restored_requests = 0
        self._init_stats(access_log)
        outer = self

        class Handler(_JsonHandler):
            server_kind = "generation"
            _outer = outer

            def do_GET(self):
                if self.path == "/health":
                    with self._track("/health"):
                        cache = outer._engine.cache
                        draining = outer._engine.draining
                        payload = {
                            "status": "draining" if draining else "ok",
                            "draining": draining,
                            "uptime_s": round(outer.uptime_s, 3),
                            "requests_total": outer.requests_served,
                            "free_pages": cache.free_pages,
                            "total_pages": cache.total_pages,
                            "page_size": cache.page_size,
                            "cached_prefix_pages":
                                cache.cached_prefix_pages,
                            "sampling_on_device":
                                outer._engine.sample_on_device,
                            "active_sequences": len(outer._engine._active),
                            "queued_sequences": len(outer._engine._sched),
                            # fleet routing (ISSUE 14): the same
                            # backoff hint a 429 would carry, scraped
                            # per probe so the router can aggregate
                            # fleet Retry-After = min over healthy
                            # replicas without a rejected request
                            "retry_after_hint":
                                outer._engine.retry_after_hint(),
                            # scheduling & multi-tenancy (ISSUE 7):
                            # per-class queue depths + the active
                            # policy knobs, so an operator can read
                            # the WFQ/chunking configuration off a
                            # live replica
                            "scheduler": outer._engine.scheduler_info(),
                            # quantized serving (ISSUE 9): the modes an
                            # operator reads off a live replica, plus
                            # the resident-KV byte accounting capacity
                            # planning needs
                            "quantize": outer._engine.quantize,
                            "kv_quant": outer._engine.kv_quant,
                            "kv_pool_bytes": cache.kv_pool_bytes,
                            "kv_scale_bytes": cache.kv_scale_bytes,
                            # tensor-parallel serving (ISSUE 20): the
                            # mesh this replica's programs compile onto
                            # plus PER-CHIP resident-KV bytes — the
                            # number capacity planning divides by, and
                            # how a fleet operator tells a TP replica
                            # from a 1-chip one at a glance
                            "tp": outer._engine.tp,
                            "mesh_shape": (
                                dict(outer._engine.mesh.shape)
                                if outer._engine.mesh is not None
                                else None),
                            "tp_quant_collectives":
                                outer._engine.tp_quant_collectives,
                            "kv_pool_bytes_per_chip":
                                cache.kv_pool_bytes_per_chip,
                            "speculative": outer._engine._spec}
                        if outer._snapshot_path:
                            payload.update({
                                "snapshot_path": outer._snapshot_path,
                                "restored_requests":
                                    outer._restored_requests})
                        if outer._journal is not None:
                            # ISSUE 13: the durability posture an
                            # operator reads off a live replica —
                            # journal path, segment count, fsync
                            # policy (and whether a hung fsync
                            # degraded it)
                            payload.update({
                                "journal": outer._journal.info(),
                                "restored_requests":
                                    outer._restored_requests})
                        if outer._engine._spec:
                            dc = outer._engine.draft_cache
                            # capacity accounting must include the
                            # draft cache (ISSUE 6 monitor satellite)
                            payload.update({
                                "spec_tokens": outer._engine.spec_k,
                                "draft_free_pages": dc.free_pages,
                                "draft_total_pages": dc.total_pages,
                                "draft_pinned_pages": dc.pinned_pages})
                        self._reply(200, payload)
                elif self.path == "/metrics":
                    with self._track("/metrics"):
                        self._reply_text(200, monitor.prometheus_text())
                elif self.path == "/debug/trace":
                    # the capture buffer as chrome-trace JSON — load it
                    # in Perfetto (ISSUE 10; tools/trace_capture.py is
                    # the CLI driver of start -> load -> stop -> GET)
                    with self._track("/debug/trace"):
                        self._reply(200, monitor.export_chrome_trace())
                elif self.path.startswith("/debug/requests/"):
                    # one request's event timeline by its stable id
                    # (route label is collapsed so ids can't explode
                    # the metrics cardinality)
                    with self._track("/debug/requests"):
                        rid = self.path[len("/debug/requests/"):]
                        tl = monitor.request_timeline(rid)
                        if tl is None:
                            self._reply(404, {
                                "error": f"no timeline for request "
                                         f"{rid!r} (tracing off, or "
                                         "evicted from the bounded "
                                         "buffer)"})
                        else:
                            self._reply(200, tl)
                elif self.path == "/debug/cost":
                    # analytical decode-program cost + process-lifetime
                    # MFU, published to /metrics as a side effect
                    # (program_flops_total / program_hbm_bytes / mfu)
                    with self._track("/debug/cost"):
                        try:
                            from ..analysis.cost import \
                                publish_engine_cost
                            self._reply(200,
                                        publish_engine_cost(outer._engine))
                        except Exception as e:  # noqa: BLE001
                            self._reply(500, {"error": str(e)})
                elif self.path.startswith("/result/"):
                    # request-id re-attach (ISSUE 10 satellite): a
                    # client that lost its stream — timeout, server
                    # restart — polls the bounded result cache; a
                    # restored request keeps its journaled id, so the
                    # SAME id works across the restart
                    with self._track("/result"):
                        rid = self.path[len("/result/"):]
                        res = outer._engine.result_for(rid)
                        if res is None:
                            self._reply(404, {
                                "error": f"unknown request id {rid!r} "
                                         "(never seen, or evicted from "
                                         "the bounded result cache)"})
                        elif res.get("status") == "pending":
                            self._reply(202, res)
                        else:
                            self._reply(200, res)
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path == "/debug/trace/start":
                    with self._track("/debug/trace/start"):
                        monitor.start_capture()
                        self._reply(200, {"capturing": True})
                    return
                if self.path == "/debug/trace/stop":
                    with self._track("/debug/trace/stop"):
                        monitor.stop_capture()
                        self._reply(200, {"capturing": False})
                    return
                if self.path == "/admin/migrate":
                    with self._track("/admin/migrate"):
                        self._do_migrate()
                    return
                if self.path != "/generate":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                with self._track("/generate"):
                    self._do_generate()

            def _do_migrate(self):
                """Journal-backed failover's far side (ISSUE 14): the
                replica supervisor POSTs a dead replica's recovered
                live set here; each snapshot-format entry flows through
                the engine's replay-admission path (``strict=False`` —
                one unplaceable request must not abort the batch; ids
                ALREADY live here dedup into ``rejected``, which makes
                a supervisor that crashed between migrate and
                source-retire safely re-runnable).  Replies with the
                ids that landed so the caller retires exactly those in
                the source journal."""
                if outer._engine.draining:
                    self._reply(503, {"error": "replica draining; "
                                      "migrate elsewhere",
                                      "draining": True})
                    return
                try:
                    body = self._read_json()
                    entries = body.get("requests", [])
                    if not isinstance(entries, list):
                        raise ValueError("requests must be a list")
                except (ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._reply(400, {"error": str(e)})
                    return
                # ids this replica ALREADY knows (live now, or finished
                # in the result cache) are the dedup outcome, not a
                # migration failure: a router retry landed them here
                # first, or an earlier crashed failover got this far —
                # report them as "live" so the supervisor retires them
                # in the source journal instead of leaving zombies
                live, todo = [], []
                for e in entries:
                    rid = e.get("request_id")
                    if rid is not None \
                            and outer._engine.result_for(rid) is not None:
                        live.append(rid)
                    else:
                        todo.append(e)
                try:
                    with warnings.catch_warnings(record=True) as wlog:
                        warnings.simplefilter("always")
                        reqs = outer._engine.restore(
                            {"version": 1, "requests": todo},
                            strict=False)
                except Exception as e:  # noqa: BLE001 — server fault
                    self._reply(500, {"error": str(e)})
                    return
                ok = [r.request_id for r in reqs]
                landed = set(ok) | set(live)
                self._reply(200, {
                    "restored": ok,
                    "live": live,
                    "rejected": [e.get("request_id") for e in entries
                                 if e.get("request_id") not in landed],
                    # per-entry skip reasons (restore warns one line
                    # per rejected entry) — the supervisor logs these,
                    # so a failed placement is diagnosable from the
                    # router side
                    "warnings": [str(w.message) for w in wlog]})

            def _do_generate(self):
                try:
                    _faults.maybe_fire("http_handler")
                    try:
                        req = self._read_json()
                        if not isinstance(req, dict):
                            raise ValueError("request body must be a "
                                             "JSON object")
                        ids = np.asarray(req["input_ids"], np.int32)
                        if ids.ndim != 2:
                            raise ValueError("input_ids must be 2-D "
                                             "(batch, seq)")
                        max_new = int(req.get("max_new_tokens", 32))
                        eos = req.get("eos_token_id")
                        do_sample = bool(req.get("do_sample", False))
                        temperature = float(req.get("temperature", 1.0))
                        ttl = req.get("timeout_s")
                        ttl = None if ttl is None else float(ttl)
                        draft = req.get("draft")
                        draft = None if draft is None else bool(draft)
                        priority = req.get("priority")
                        priority = (None if priority is None
                                    else str(priority))
                        tenant = str(req.get("tenant", "default"))
                        request_id = req.get("request_id")
                        request_id = (None if request_id is None
                                      else str(request_id))
                        with outer._count_lock:
                            outer._request_count += 1
                            seed = int(req.get("seed",
                                               outer._request_count))
                    except (KeyError, ValueError, TypeError,
                            json.JSONDecodeError) as e:
                        self._reply(400, {"error": str(e)})
                        return
                    try:
                        out, rows = outer._engine.generate_with_requests(
                            ids, max_new_tokens=max_new, eos_token_id=eos,
                            do_sample=do_sample, temperature=temperature,
                            seed=seed, ttl_s=ttl, draft=draft,
                            priority=priority, tenant=tenant,
                            request_id=request_id)
                    except ValueError as e:      # request-shape problems
                        # e.g. prompt + max_new_tokens past the rope
                        # table: the CLIENT's request is wrong — 400,
                        # never the retryable 503 (regression-locked in
                        # tests/test_engine_faults.py)
                        self._reply(400, {"error": str(e)})
                        return
                    self._reply(200, {
                        "output_ids": out.tolist(),
                        "new_tokens": int(out.shape[1] - ids.shape[1]),
                        # the stable per-row ids (ISSUE 10): the
                        # /result/<id> and /debug/requests/<id> handles
                        "request_ids": [r.request_id for r in rows]})
                except EngineSaturated as e:
                    # bounded-queue overflow: retryable — the hint is
                    # the REQUESTING CLASS's backlog's estimated
                    # service time (its queue depth x measured
                    # decode-step p50, clamped to [1, 30]s): a chat
                    # client is never told to back off for the batch
                    # queue's sins.  An admission SHED (ISSUE 19)
                    # carries its own projected-wait hint, computed
                    # at the decision — prefer it over re-deriving
                    hint = getattr(e, "retry_after_s", None)
                    cls = getattr(e, "priority_class", None) or priority
                    if hint is None:
                        hint = outer._engine.retry_after_hint(cls)
                    self._reply(429, {"error": str(e)}, headers={
                        "Retry-After": str(hint)})
                except EngineDraining as e:
                    self._reply(503, {"error": str(e), "draining": True})
                except DeadlineExceeded as e:
                    self._reply(504, {"error": str(e)})
                except RuntimeError as e:
                    # capacity (page-pool) exhaustion: retryable
                    self._reply(503, {"error": str(e)})
                except Exception as e:   # noqa: BLE001 — server fault
                    self._reply(500, {"error": str(e)})

        try:
            self._httpd = ThreadingHTTPServer((host, port), Handler)
        except BaseException:
            # heartbeat-leak fix (ISSUE 14 satellite): a bind failure —
            # a supervisor restarting a replica in-process on a port
            # its predecessor is still releasing hits exactly this —
            # must not leak the already-running engine: its scheduler
            # thread, its step_timeout_s watchdog heartbeat (which
            # would fire comm_timeouts_total against a dead engine
            # forever) and the journal's writer thread + fsync
            # heartbeat all deregister here
            self._engine.stop()
            if self._journal is not None:
                self._journal.close()
            raise
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # crash consistency (ISSUE 8): consume a predecessor's journal
        # AFTER the listener socket bound (a bind failure — e.g. the
        # predecessor still releasing the port — must not have eaten
        # the journal) but before serve_forever starts: restored
        # requests are decoding by the time the first request arrives
        if self._journal is not None:
            self._restored_requests = self._restore_journal()
        elif snapshot_path and os.path.exists(snapshot_path):
            self._restored_requests = self._restore_snapshot(snapshot_path)

    # ------------------------------------- write-ahead journal (ISSUE 13)
    def _restore_journal(self) -> int:
        """Resubmit the live set the journal recovered — each entry
        flows through the engine's replay-admission path exactly like
        a snapshot restore (``strict=False``: one unplaceable request
        must not abort the whole resume).  Entries the engine rejected
        are retired in the journal as ``unrestorable`` so they cannot
        zombie through every future compaction."""
        entries = self._journal_entries
        if not entries:
            return 0
        try:
            reqs = self._engine.restore({"version": 1,
                                         "requests": entries},
                                        strict=False)
        except Exception as e:  # noqa: BLE001 — degrade, never block
            warnings.warn(f"journal restore failed: {e!r}")  # startup
            return 0
        ok = {r.request_id for r in reqs}
        for e in entries:
            rid = e.get("request_id")
            if rid is not None and rid not in ok:
                self._journal.append_retire(rid, why="unrestorable")
        return len(reqs)

    # ----------------------------------------------- snapshot (ISSUE 8)
    def _restore_snapshot(self, path: str) -> int:
        """Consume a predecessor's journal: rename first (a crash
        mid-restore must not double-resume), then resubmit every entry
        through the engine's replay primitive — per-entry failures are
        warned about, never fatal (strict=False)."""
        consumed = path + ".restored"
        try:
            os.replace(path, consumed)
            with open(consumed) as f:
                snap = json.load(f)
        except (OSError, ValueError) as e:
            warnings.warn(f"snapshot restore skipped: {e!r}")
            return 0
        try:
            return len(self._engine.restore(snap, strict=False))
        except Exception as e:  # noqa: BLE001 — a malformed journal
            # (valid JSON, wrong shape) must degrade to an empty
            # resume, never keep the server from starting
            warnings.warn(f"snapshot restore failed: {e!r}")
            return 0

    def save_snapshot(self, path: Optional[str] = None) -> int:
        """Journal every in-flight request to ``path`` (default: the
        configured ``snapshot_path``) atomically; returns the request
        count.  The engine quiesces at a step boundary first, so the
        journal is a consistent cut a restarted process resumes
        bit-exactly."""
        path = path or self._snapshot_path
        if not path:
            raise ValueError("no snapshot_path configured")
        snap = self._engine.snapshot()
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(snap, f)
        # durability bugfix (ISSUE 13 satellite): a bare os.replace
        # never fsyncs the file or the parent directory, so the rename
        # itself could be lost on power failure — the journal's shared
        # helper syncs both
        from .journal import durable_replace
        durable_replace(tmp, path)
        return len(snap["requests"])

    # ------------------------------------------------- graceful shutdown
    @property
    def draining(self) -> bool:
        return self._engine.draining

    def begin_drain(self, timeout: Optional[float] = None,
                    reject_queued: bool = False) -> None:
        """Start a graceful drain WITHOUT blocking (idempotent): the
        engine stops admitting — new /generate requests get 503 with
        ``"draining": true`` and /health flips to ``"draining"`` —
        while every in-flight generation runs to completion.  The HTTP
        listener stays up throughout so clients can still poll /health
        and /metrics.  ``reject_queued=True`` is the hard-preemption
        fast path: queued-but-unadmitted requests fail immediately
        instead of being completed first."""
        if self._drain_thread is not None and self._drain_thread.is_alive():
            return
        self._drain_result = None

        def _drain():
            self._drain_result = self._engine.drain(
                timeout=timeout, reject_queued=reject_queued)

        self._drain_thread = threading.Thread(
            target=_drain, name="server-drain", daemon=True)
        self._drain_thread.start()

    def wait_drained(self, timeout: Optional[float] = None) -> bool:
        """Block until a begin_drain() started earlier finishes;
        True if it completed within ``timeout``."""
        t = self._drain_thread
        if t is None:
            eng = self._engine
            with eng._cond:
                return not (eng._active or len(eng._sched)
                            or eng._prefilling or eng._preempted)
        t.join(timeout)
        return not t.is_alive()

    def attach_preemption(self, handler,
                          drain_timeout: Optional[float] = None) -> None:
        """Wire a distributed.fault_tolerance.PreemptionHandler: on
        SIGTERM (the TPU pod preemption notice) the server begins a
        graceful drain — the resilience contract's 'finish what you
        admitted, reject what you have not' shutdown.  With
        ``snapshot_path`` configured the drain is bracketed by
        snapshots (ISSUE 8): one taken IMMEDIATELY (the crash floor —
        if the grace period ends mid-drain, everything in flight is
        journaled) and one refreshed when the drain settles, so
        requests the drain DID finish are dropped from the journal and
        never re-executed by the relaunched process; whatever the
        drain window was too short to finish resumes exactly."""
        def drain_on_preemption():
            # stop admissions SYNCHRONOUSLY first: begin_drain only
            # spawns the drain thread, and a request admitted before
            # that thread flips the flag would be journal-invisible
            # and lost if the grace period ends mid-drain
            self._engine.stop_admissions()
            self.begin_drain(timeout=drain_timeout)
            if self._journal is not None:
                # ISSUE 13: the WAL already holds every in-flight
                # request — the SIGTERM "snapshot" collapses to one
                # durable flush (the crash floor) plus a final
                # compaction once the drain truly completed, so a
                # relaunch resumes exactly what the grace period was
                # too short to finish and nothing more
                try:
                    self._journal.flush(sync=True, timeout=30.0)
                except Exception as e:  # noqa: BLE001 — drain anyway
                    warnings.warn(f"pre-drain journal flush failed: "
                                  f"{e!r}")

                def _refresh_journal():
                    if self.wait_drained(None) and self._drain_result:
                        try:
                            self._journal.compact(wait=True,
                                                  timeout=30.0)
                        except Exception as e:  # noqa: BLE001 — keep
                            # the crash-floor journal rather than none
                            warnings.warn(
                                f"post-drain journal compaction "
                                f"failed: {e!r}")
                threading.Thread(target=_refresh_journal, daemon=True,
                                 name="journal-refresh").start()
            elif self._snapshot_path:
                try:
                    self.save_snapshot()
                except Exception as e:   # noqa: BLE001 — the drain
                    # must still happen even if the journal write fails
                    warnings.warn(f"pre-drain snapshot failed: {e!r}")
                def _refresh():
                    # shrink the journal ONLY after a drain that
                    # actually COMPLETED its requests — a timed-out
                    # drain or a hard stop() (which ERRORS the
                    # remainder) must keep the crash-floor journal, or
                    # the relaunch would resume nothing.  The wait is
                    # unbounded: the drain thread itself terminates at
                    # ITS deadline, and racing it with the same
                    # timeout would skip the refresh for a drain that
                    # finished right at the wire
                    if self.wait_drained(None) and self._drain_result:
                        try:
                            self.save_snapshot()
                        except Exception as e:  # noqa: BLE001 — keep
                            # the crash-floor journal rather than none
                            warnings.warn(
                                f"post-drain snapshot refresh failed: "
                                f"{e!r}")
                threading.Thread(target=_refresh, daemon=True,
                                 name="snapshot-refresh").start()
        handler.on_preemption(drain_on_preemption)

    def stop(self):
        super().stop()
        self._engine.stop()
        if self._drain_thread is not None:
            self._drain_thread.join(timeout=5)
            self._drain_thread = None
        if self._journal is not None:
            # closing flushes + final-fsyncs but deliberately does NOT
            # retire live entries: a stop without retirement is the
            # crash floor a relaunched server resumes from
            self._journal.close()


def serve(model_prefix: str, host: str = "127.0.0.1", port: int = 8000,
          pool_size: int = 1):
    """Blocking CLI-style entry: serve the model until interrupted."""
    server = InferenceServer(model_prefix, host, port, pool_size)
    print(f"serving {model_prefix} at http://{server.host}:{server.port}")
    try:
        server._httpd.serve_forever()
    except KeyboardInterrupt:
        server.stop()
