"""HTTP model server over the Predictor (reference: the C++ fluid
inference server / Paddle Serving's role — here a dependency-free
stdlib implementation fronting the StableHLO Predictor).

Endpoints (JSON; arrays as nested lists with dtype strings):
  GET  /health          -> {"status": "ok", "model": prefix}
  GET  /metadata        -> input/output names
  POST /predict         -> {"inputs": {name: {"data": [...], "dtype": ...,
                            "shape": [...]}}} -> {"outputs": {...}}

A PredictorPool serves concurrent requests; the ThreadingHTTPServer
dispatches each request to a pool slot.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from . import Config, Predictor, PredictorPool

__all__ = ["InferenceServer", "serve"]


class InferenceServer:
    """Serve a jit.save artifact over HTTP.

    Usage::

        server = InferenceServer("ckpt/model", device="cpu", pool_size=2)
        server.start()              # non-blocking; .port has the port
        ...
        server.stop()
    """

    def __init__(self, model_prefix: str, host: str = "127.0.0.1",
                 port: int = 0, pool_size: int = 1, device: str = ""):
        config = Config(model_prefix)
        if device == "cpu":
            config.disable_gpu()
        elif device not in ("", "tpu", "gpu"):
            raise ValueError(
                f"device must be '', 'cpu', 'tpu' or 'gpu', got {device!r}")
        self._prefix = model_prefix
        self._pool = PredictorPool(config, pool_size)
        self._pool_lock = threading.Lock()
        self._next = [0]
        self._size = pool_size
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):   # quiet by default
                pass

            def _reply(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    self._reply(200, {"status": "ok",
                                      "model": outer._prefix})
                elif self.path == "/metadata":
                    p = outer._pool.retrieve(0)
                    self._reply(200, {
                        "inputs": p.get_input_names(),
                        "outputs": p.get_output_names()})
                else:
                    self._reply(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                if self.path != "/predict":
                    self._reply(404, {"error": f"no route {self.path}"})
                    return
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n))
                    out = outer._predict(req)
                    self._reply(200, out)
                except Exception as e:   # noqa: BLE001
                    self._reply(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    def _predict(self, req):
        inputs = req.get("inputs", {})
        with self._pool_lock:
            idx = self._next[0] % self._size
            self._next[0] += 1
        pred = self._pool.retrieve(idx)
        names = pred.get_input_names()
        missing = [n for n in names if n not in inputs]
        if missing:
            raise ValueError(f"missing inputs: {missing}")
        arrays = []
        for name in names:
            spec = inputs[name]
            arr = np.asarray(spec["data"],
                             dtype=spec.get("dtype", "float32"))
            if "shape" in spec:
                arr = arr.reshape(spec["shape"])
            arrays.append(arr)
        # handle-free run: inputs are passed per call and outputs returned
        # directly, so concurrent requests sharing a pool slot never race
        # through the copy_from_cpu/run/copy_to_cpu handle state
        results = pred.run(arrays)
        outputs = {}
        for name, out in zip(pred.get_output_names(), results):
            a = np.asarray(out)
            outputs[name] = {"data": a.tolist(), "dtype": str(a.dtype),
                             "shape": list(a.shape)}
        return {"outputs": outputs}

    # ------------------------------------------------------------------
    def start(self):
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()
        return False


def serve(model_prefix: str, host: str = "127.0.0.1", port: int = 8000,
          pool_size: int = 1):
    """Blocking CLI-style entry: serve the model until interrupted."""
    server = InferenceServer(model_prefix, host, port, pool_size)
    print(f"serving {model_prefix} at http://{server.host}:{server.port}")
    try:
        server._httpd.serve_forever()
    except KeyboardInterrupt:
        server.stop()
