"""Speculative decoding: a small draft model proposes, the target model
verifies k tokens in ONE forward (reference serving capability class:
the speculative/draft-verify path of PaddleNLP's block-attention serving
on top of paddle/phi/kernels/fusion/gpu/block_multi_head_attention;
algorithm: Leviathan et al. 2023, greedy variant).

TPU-native framing: verification is a single batched forward over the k
proposed tokens — one MXU-friendly [B, k, H] pass instead of k
sequential [B, 1, H] decode steps — so acceptance rate directly converts
HBM-bandwidth-bound decode steps into compute-dense verify steps.

Greedy speculative decoding is EXACT: the emitted sequence is
bit-identical to target-only greedy decoding, whatever the draft
proposes (every accepted token equals the target's argmax given its
prefix, and the first disagreement emits the target's own argmax).  The
equivalence test in tests/test_speculative.py asserts that.

KV caches are plain per-layer (k, v) concat caches (the eager
LlamaModel cache path); rejected speculative suffixes are rolled back
by :class:`_RollbackKV` — the pre-round cache stays alive as the base
and only the appended block's ACCEPTED prefix is sliced out, so a
rollback costs O(accepted tokens), never an O(T) full-cache rebuild
(regression-locked in tests/test_speculative.py).
"""
from __future__ import annotations

import time as _time
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..framework.tape import no_grad
from ..framework.tensor import wrap_array
from .. import tensor as _T


from ..models.llama import empty_kv_caches as _empty_caches


class _RollbackKV:
    """Concat-KV cache with O(appended) rollback.

    The materialized per-layer caches fed to the last forward stay
    alive as ``base``; a speculative round's outcome is absorbed by
    slicing ONLY the new block's accepted prefix into ``tail`` — never
    by re-slicing the full [T]-length cache (the old ``_trim_caches``
    rebuilt every layer's whole cache every round).  ``feed()`` merges
    base+tail once, immediately before the next forward — where a
    same-size concat (the model's own cache append) happens anyway, so
    the merge adds no asymptotic cost while the rollback itself drops
    from O(T) to O(accepted)."""

    __slots__ = ("base", "tail")

    def __init__(self, caches):
        self.base = caches          # list[(k, v)], k/v (1, T, kvh, d)
        self.tail = None

    @property
    def length(self) -> int:
        n = int(self.base[0][0].shape[1])
        if self.tail is not None:
            n += int(self.tail[0][0].shape[1])
        return n

    def feed(self):
        """Materialized per-layer caches for the next model() call
        (merges any pending tail into the base, one concat per layer)."""
        if self.tail is not None:
            self.base = [
                (_T.concat([bk, tk], axis=1), _T.concat([bv, tv], axis=1))
                for (bk, bv), (tk, tv) in zip(self.base, self.tail)]
            self.tail = None
        return self.base

    def absorb(self, full_caches, keep: int) -> None:
        """Record a round's outcome: ``full_caches`` is what the model
        returned (the fed base plus the appended block); keep the first
        ``keep`` positions.  The base is untouched — identity-preserved,
        the no-copy regression lock — and only [base_len:keep) is
        sliced out of the block, O(keep - base_len) per layer."""
        assert self.tail is None, "absorb() must follow a feed()"
        base_len = int(self.base[0][0].shape[1])
        if keep <= base_len:
            return
        self.tail = [(k[:, base_len:keep], v[:, base_len:keep])
                     for k, v in full_caches]


class SpeculativeGenerator:
    """Greedy speculative decoding over (target, draft) causal LMs.

    Both models must expose the ``model(ids, position_offset, kv_caches)
    -> (hidden, new_caches)`` cache path and a logits head (LlamaForCausalLM
    / LlamaMoeForCausalLM shape).  ``num_speculative_tokens`` is the
    draft lookahead k; acceptance statistics land in ``last_stats``.
    """

    def __init__(self, target_model, draft_model,
                 num_speculative_tokens: int = 4):
        if num_speculative_tokens < 1:
            raise ValueError("num_speculative_tokens must be >= 1")
        self.target = target_model
        self.draft = draft_model
        self.k = int(num_speculative_tokens)
        self.last_stats: dict = {}

    # ------------------------------------------------------------ internals
    def _logits(self, model, hidden):
        return model.lm_head(hidden) if model.lm_head is not None \
            else model._logits_of(hidden)

    def _argmax(self, logits) -> np.ndarray:
        return np.asarray(
            jnp.argmax(logits._data[:, -1].astype(jnp.float32), axis=-1))

    # ------------------------------------------------------------- generate
    def generate(self, input_ids, max_new_tokens: int = 32,
                 eos_token_id: Optional[int] = None):
        """Greedy decode; batch 1 per call (verification rollback is
        per-sequence).  Returns the full [1, prompt+new] id array."""
        ids = np.asarray(input_ids._data if hasattr(input_ids, "_data")
                         else input_ids)
        if ids.ndim == 1:
            ids = ids[None, :]
        if ids.shape[0] != 1:
            raise ValueError("speculative generate is per-sequence "
                             "(batch 1); batch via the serving engine")
        t0 = _time.perf_counter()
        proposed = accepted = rounds = 0
        with no_grad():
            x = wrap_array(jnp.asarray(ids, jnp.int32))
            # prefill both models on the prompt
            h, caches = self.target.model(x, 0,
                                          _empty_caches(self.target, 1))
            tgt = _RollbackKV(caches)
            nxt = int(self._argmax(self._logits(self.target, h[:, -1:]))[0])
            _, caches = self.draft.model(x, 0,
                                         _empty_caches(self.draft, 1))
            dft = _RollbackKV(caches)
            # expose the live cache state for the rollback regression
            # tests (identity of the base across a rejected round)
            self._tgt_kv, self._dft_kv = tgt, dft
            out = list(ids[0]) + [nxt]
            # invariant: caches cover out[:-1]; out[-1] is unverified input
            while len(out) - ids.shape[1] < max_new_tokens:
                if eos_token_id is not None and out[-1] == eos_token_id:
                    break
                rounds += 1
                L = len(out) - 1          # verified cached positions
                budget = max_new_tokens - (len(out) - ids.shape[1])
                k = min(self.k, budget)
                # the draft cache can trail L (an all-accepted round
                # produces its last token without ever feeding it);
                # ingest the gap in one forward before proposing — gap
                # tokens are VERIFIED, so the filled cache becomes the
                # round's rollback base
                dfeed = dft.feed()
                dft_len = int(dfeed[0][0].shape[1])
                if dft_len < L:
                    fill = wrap_array(jnp.asarray(
                        [out[dft_len:L]], jnp.int32))
                    _, dfeed = self.draft.model(fill, dft_len, dfeed)
                    dft.base = dfeed
                # ---- draft proposes k tokens autoregressively --------
                draft_tokens = []
                cur = out[-1]
                dwork = dfeed
                for _ in range(k):
                    step = wrap_array(jnp.asarray([[cur]], jnp.int32))
                    dh, dwork = self.draft.model(
                        step, L + len(draft_tokens), dwork)
                    cur = int(self._argmax(
                        self._logits(self.draft, dh))[0])
                    draft_tokens.append(cur)
                proposed += k
                # ---- target verifies in ONE forward over k+1 tokens --
                block = np.asarray([[out[-1]] + draft_tokens], np.int32)
                tfeed = tgt.feed()
                th, tfull = self.target.model(
                    wrap_array(jnp.asarray(block)), L, tfeed)
                tlogits = self._logits(self.target, th)
                targets = np.asarray(jnp.argmax(
                    tlogits._data[0].astype(jnp.float32), axis=-1))
                # targets[i] = target's next token after block[:i+1]
                n_ok = 0
                while n_ok < k and draft_tokens[n_ok] == int(targets[n_ok]):
                    n_ok += 1
                accepted += n_ok
                emitted = draft_tokens[:n_ok] + [int(targets[n_ok])] \
                    if n_ok < k else draft_tokens + [int(targets[k])]
                out.extend(emitted)
                # ---- O(accepted) rollback: keep the fed base alive and
                # slice only the accepted prefix out of the new block —
                # rejected suffixes simply never enter the cache ----
                new_len = len(out) - 1
                tgt.absorb(tfull, new_len)
                dft.absorb(dwork, min(new_len, L + k))
                if eos_token_id is not None and eos_token_id in emitted:
                    cut = emitted.index(eos_token_id)
                    out = out[:len(out) - len(emitted) + cut + 1]
                    break
        out = out[:ids.shape[1] + max_new_tokens]
        self.last_stats = {
            "rounds": rounds,
            "proposed": proposed,
            "accepted": accepted,
            "acceptance_rate": round(accepted / max(proposed, 1), 3),
            "tokens_per_round": round(
                (len(out) - ids.shape[1]) / max(rounds, 1), 2),
            "seconds": round(_time.perf_counter() - t0, 4),
        }
        return np.asarray([out], dtype=np.int64)
