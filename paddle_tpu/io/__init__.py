"""Data loading: Dataset/Sampler/DataLoader.

Capability parity: python/paddle/io/ in the reference (reader.py:262
DataLoader, dataloader/worker.py multiprocess workers, batch samplers,
dataset utilities).

TPU-native: workers produce numpy batches on the host; transfer to device is
a single `jax.device_put` per batch (the reference's pin-memory +
double-buffer reader ops collapse into PJRT's async h2d).  A prefetch queue
overlaps host-side loading with device compute.
"""
from __future__ import annotations

import bisect
import itertools
import math
import queue
import threading
import time
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from .. import monitor
from ..framework.tensor import Tensor, to_tensor, wrap_array
from ..framework import random as _random

# input-pipeline telemetry (ISSUE 5): how long the consumer (training
# loop) sat blocked waiting for the next batch — the number the device
# prefetch stage exists to drive toward zero
_input_wait_s = monitor.histogram(
    "input_wait_seconds", "time the DataLoader consumer spent blocked "
    "waiting for the next batch")


class Dataset:
    """reference: paddle.io.Dataset (map-style)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(
            len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        lengths = [int(math.floor(n * frac)) for frac in lengths]
        for i in range(n - sum(lengths)):
            lengths[i % len(lengths)] += 1
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = np.random.permutation(len(dataset)).tolist()
    out, offset = [], 0
    for length in lengths:
        out.append(Subset(dataset, perm[offset:offset + length]))
        offset += length
    return out


class Sampler:
    """reference: paddle.io.Sampler."""

    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: paddle.io.BatchSampler."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference: paddle.io.DistributedBatchSampler — shards indices per rank.

    On TPU SPMD the common path shards the *global batch array* instead, but
    the per-rank sampler is kept for multi-host input pipelines.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        from ..distributed import get_world_size, get_rank
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """reference: python/paddle/io/dataloader/collate.py."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    return to_tensor(np.asarray(batch))


class _PrefetchIter:
    """Background-thread prefetcher (host-side pipeline overlap), with an
    optional DEVICE stage (ISSUE 5): when ``device_fn`` is given, a
    second thread applies it (``jax.device_put`` honoring an optional
    sharding) to each host batch and double-buffers the result in its
    own bounded queue — the next batch's h2d transfer is issued from the
    prefetch pipeline and overlaps the current step's compute, instead
    of serializing on the consumer thread.

    ``close()`` (also triggered by exhaustion, producer error, and GC)
    shuts every pipeline thread down without leaks, even when the
    consumer abandons the iterator mid-epoch with full queues — all
    queue puts poll a stop event instead of blocking forever."""

    _POLL_S = 0.1

    def __init__(self, producer, depth, device_fn=None, device_depth=2):
        # the thread closures must capture ONLY these locals, never
        # ``self``: a thread frame holding the iterator would keep it
        # reachable forever, so __del__ (the abandon-path shutdown)
        # could never fire and the threads would leak
        done = self._done = object()
        stop = self._stop = threading.Event()
        exc_box = self._exc_box = [None]
        poll = self._POLL_S
        host_q = queue.Queue(maxsize=depth)
        self._q = host_q
        self.threads: List[threading.Thread] = []

        def put(q, item) -> bool:
            while not stop.is_set():
                try:
                    q.put(item, timeout=poll)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                for item in producer:
                    if not put(host_q, item):
                        return
            except BaseException as e:  # propagate into consumer
                if exc_box[0] is None:
                    exc_box[0] = e
            finally:
                put(host_q, done)

        self.threads.append(threading.Thread(
            target=produce, name="dataloader-prefetch", daemon=True))
        if device_fn is not None:
            dev_q = queue.Queue(maxsize=max(device_depth, 1))
            self._q = dev_q

            def stage():
                try:
                    while not stop.is_set():
                        try:
                            item = host_q.get(timeout=poll)
                        except queue.Empty:
                            continue
                        if item is done or \
                                not put(dev_q, device_fn(item)):
                            return
                except BaseException as e:
                    if exc_box[0] is None:
                        exc_box[0] = e
                finally:
                    put(dev_q, done)

            self.threads.append(threading.Thread(
                target=stage, name="dataloader-device-stage", daemon=True))
        for t in self.threads:
            t.start()

    @property
    def _exc(self):
        return self._exc_box[0]

    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        t0 = time.perf_counter()
        while True:
            try:
                item = self._q.get(timeout=self._POLL_S)
                break
            except queue.Empty:
                if not self._stop.is_set() and \
                        any(t.is_alive() for t in self.threads):
                    continue
                # the threads are gone (or we were closed): anything
                # they enqueued is already visible — drain before
                # declaring exhaustion, or the epoch's tail batches
                # would be silently dropped
                try:
                    item = self._q.get_nowait()
                    break
                except queue.Empty:
                    _input_wait_s.observe(time.perf_counter() - t0)
                    self.close()
                    if self._exc is not None:
                        raise self._exc
                    raise StopIteration
        _input_wait_s.observe(time.perf_counter() - t0)
        if item is self._done:
            self.close()
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item

    def close(self):
        """Stop the pipeline threads (idempotent; safe mid-epoch — the
        threads observe the stop event at their next queue poll)."""
        self._stop.set()
        for t in self.threads:
            if t is not threading.current_thread():
                t.join(timeout=5)

    def __del__(self):
        self._stop.set()


class DataLoader:
    """reference: paddle.io.DataLoader (reader.py:262).

    num_workers>0 uses multiprocessing workers feeding an index queue
    (reference: io/dataloader/worker.py); prefetch_factor batches are staged
    ahead on a background thread either way.

    Device prefetch (ISSUE 5): ``device_prefetch=True`` adds a device
    stage to the prefetch pipeline — each batch's ``jax.device_put`` is
    issued from a pipeline thread (honoring ``device_sharding``, e.g. a
    dp-mesh NamedSharding) and double-buffered ``device_prefetch_depth``
    deep, so the next batch's h2d transfer overlaps the current step's
    compute instead of paying on the consumer thread.  Defaults on when
    a ``device_sharding`` is given.  The staged batches are bit-identical
    to an eager ``device_put`` of the host batch (regression-locked in
    tests/test_dataloader_prefetch.py).
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False, device_prefetch=None,
                 device_sharding=None, device_prefetch_depth=2):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.device_sharding = device_sharding
        self.device_prefetch = (device_sharding is not None
                                if device_prefetch is None
                                else bool(device_prefetch))
        self.device_prefetch_depth = max(int(device_prefetch_depth), 1)
        self._payload = None
        self._pool = None
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _produce(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        if self.num_workers > 0:
            yield from self._produce_mp()
            return
        yield from self._produce_sp()

    def _produce_sp(self):
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _pickle_payload(self):
        """Pre-pickle the worker payload once (spawn children unpickle it
        after pinning CPU).  _PICKLE_FAILED when not spawn-picklable."""
        import pickle
        import warnings

        if self._payload is not None:
            return self._payload
        try:
            self._payload = pickle.dumps(
                (self.dataset, self.collate_fn, self.worker_init_fn))
        except Exception as e:  # noqa: BLE001 — lambdas/closures/local classes
            warnings.warn(
                f"num_workers={self.num_workers} needs a picklable dataset/"
                f"collate_fn/worker_init_fn under the spawn start method "
                f"({e!r}); falling back to in-process loading", stacklevel=3)
            self._payload = _PICKLE_FAILED
        return self._payload

    def _produce_mp(self):
        # spawn, not fork: forking a multithreaded (jax) parent deadlocks.
        # The worker payload is pre-pickled in the parent and only unpickled
        # in the child AFTER it pins the CPU backend, so materializing any
        # Tensors in the dataset cannot touch (and hang on) a sick TPU plugin.
        if self._pickle_payload() is _PICKLE_FAILED:
            yield from self._produce_sp()
            return
        # a pool serves one epoch at a time; a second concurrent iterator
        # (or a pool whose workers died) gets a fresh private pool
        pool = self._pool
        private = pool is None or pool.busy or not pool.alive()
        if private:
            pool = _WorkerPool(self._payload, self.num_workers,
                               self.prefetch_factor)
            if self._pool is None and self.persistent_workers:
                self._pool, private = pool, False
        try:
            yield from pool.run_epoch(list(self.batch_sampler), self.timeout)
        finally:
            if private or not self.persistent_workers:
                pool.shutdown()
                if pool is self._pool:
                    self._pool = None

    def __del__(self):
        pool = getattr(self, "_pool", None)
        if pool is not None:
            pool.shutdown()

    def _device_stage_fn(self):
        """The device stage run on the prefetch pipeline thread: one
        ``jax.device_put`` per array leaf, honoring an optional
        sharding (dp meshes shard the global batch here, off the
        consumer thread)."""
        import jax
        sharding = self.device_sharding

        def put(arr):
            return (jax.device_put(arr, sharding)
                    if sharding is not None else jax.device_put(arr))

        def stage(obj):
            if isinstance(obj, Tensor):
                return wrap_array(put(obj._data))
            if isinstance(obj, np.ndarray):
                return wrap_array(put(obj))
            if isinstance(obj, (list, tuple)):
                return type(obj)(stage(o) for o in obj)
            if isinstance(obj, dict):
                return {k: stage(v) for k, v in obj.items()}
            return obj
        return stage

    def __iter__(self):
        return _PrefetchIter(
            self._produce(), self.prefetch_factor,
            device_fn=self._device_stage_fn() if self.device_prefetch
            else None,
            device_depth=self.device_prefetch_depth)


class _WorkerPool:
    """Spawn-based DataLoader worker pool (reference: io/dataloader/worker.py
    + reader.py _DataLoaderIterMultiProcess).

    Reusable across epochs when persistent_workers=True — workers are
    stateless per index-batch, so an epoch is just a numbered stream of
    (seq, indices) items with exactly-once accounting in the parent.
    """

    def __init__(self, payload, num_workers, prefetch_factor):
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        self.index_q = ctx.Queue()
        self.out_q = ctx.Queue(maxsize=num_workers * prefetch_factor)
        self.busy = False
        self._gen = 0   # epoch generation: stale items from an abandoned
        self.workers = [  # epoch are dropped by tag, not mistaken for data
            ctx.Process(target=_mp_worker_boot,
                        args=(payload, w, self.index_q, self.out_q),
                        daemon=True)
            for w in range(num_workers)
        ]
        for w in self.workers:
            w.start()

    def alive(self):
        return all(w.is_alive() for w in self.workers)

    def run_epoch(self, batches, timeout=0):
        self.busy = True
        try:
            yield from self._run_epoch(batches, timeout)
        finally:
            self.busy = False

    def _run_epoch(self, batches, timeout):
        self._gen += 1
        gen = self._gen
        for seq, indices in enumerate(batches):
            self.index_q.put((gen, seq, indices))
        pending = {}
        next_seq = 0
        received = 0
        waited = 0.0
        while received < len(batches):
            try:
                g, seq, batch, err = self.out_q.get(timeout=_POLL_S)
            except queue.Empty:
                # liveness check: a worker that died (unpicklable payload
                # class in the child, worker_init_fn crash, OOM-kill) must
                # surface as an error, not a parent hang
                dead = [w.name for w in self.workers if not w.is_alive()]
                if dead:
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader worker(s) {dead} exited unexpectedly "
                        f"(check child stderr; spawned workers must be able "
                        f"to import the dataset/collate_fn module)")
                waited += _POLL_S
                if timeout and waited >= timeout:
                    self.shutdown()
                    raise TimeoutError(
                        f"DataLoader batch not produced within {timeout}s")
                continue
            waited = 0.0
            if g != gen:
                continue   # leftover from an abandoned earlier epoch
            received += 1
            if err is not None:
                self.shutdown()
                raise err
            pending[seq] = batch
            while next_seq in pending:
                yield _from_numpy_batch(pending.pop(next_seq))
                next_seq += 1

    def shutdown(self):
        for w in self.workers:
            if w.is_alive():
                w.terminate()
        for w in self.workers:
            w.join(timeout=5)


_POLL_S = 2.0
_PICKLE_FAILED = object()   # distinct from the "not yet computed" None


def _mp_worker_boot(payload, wid, index_q, out_q):
    """Spawned DataLoader worker entry (reference: io/dataloader/worker.py).

    Must be a module-level function (spawn pickles the target).  Pins the CPU
    backend before unpickling the payload — workers never need the
    accelerator, and a wedged TPU plugin must not hang the fleet
    (framework/backend_guard.py docstring).
    """
    from paddle_tpu.framework.backend_guard import helper_process_init
    helper_process_init()
    import pickle

    dataset, collate_fn, worker_init_fn = pickle.loads(payload)
    if worker_init_fn is not None:
        worker_init_fn(wid)
    while True:
        item = index_q.get()
        if item is None:
            break
        gen, seq, indices = item
        try:
            batch = collate_fn([dataset[i] for i in indices])
            # Tensors don't pickle across processes cheaply; send numpy
            out_q.put((gen, seq, _to_numpy_batch(batch), None))
        except Exception as e:  # noqa: BLE001
            out_q.put((gen, seq, None, e))


def _to_numpy_batch(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_batch(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_batch(v) for k, v in obj.items()}
    return obj


def _from_numpy_batch(obj):
    if isinstance(obj, np.ndarray):
        return to_tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_numpy_batch(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _from_numpy_batch(v) for k, v in obj.items()}
    return obj


def get_worker_info():
    return None


class SubsetRandomSampler(Sampler):
    """reference: io/sampler.py SubsetRandomSampler — sample the given
    indices without replacement, in random order."""

    def __init__(self, indices):
        if len(indices) == 0:
            raise ValueError(
                "SubsetRandomSampler requires a non-empty index list")
        self.indices = list(indices)

    def __iter__(self):
        import numpy as _np
        order = _np.random.permutation(len(self.indices))
        return iter([self.indices[i] for i in order])

    def __len__(self):
        return len(self.indices)
