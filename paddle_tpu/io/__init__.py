"""Data loading: Dataset/Sampler/DataLoader.

Capability parity: python/paddle/io/ in the reference (reader.py:262
DataLoader, dataloader/worker.py multiprocess workers, batch samplers,
dataset utilities).

TPU-native: workers produce numpy batches on the host; transfer to device is
a single `jax.device_put` per batch (the reference's pin-memory +
double-buffer reader ops collapse into PJRT's async h2d).  A prefetch queue
overlaps host-side loading with device compute.
"""
from __future__ import annotations

import bisect
import itertools
import math
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..framework.tensor import Tensor, to_tensor
from ..framework import random as _random


class Dataset:
    """reference: paddle.io.Dataset (map-style)."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            out.extend(sample if isinstance(sample, (tuple, list)) else [sample])
        return tuple(out)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative_sizes = list(itertools.accumulate(
            len(d) for d in self.datasets))

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        ds_idx = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    if all(isinstance(l, float) for l in lengths) and \
            abs(sum(lengths) - 1.0) < 1e-6:
        n = len(dataset)
        lengths = [int(math.floor(n * frac)) for frac in lengths]
        for i in range(n - sum(lengths)):
            lengths[i % len(lengths)] += 1
    if sum(lengths) != len(dataset):
        raise ValueError("sum of lengths must equal dataset length")
    perm = np.random.permutation(len(dataset)).tolist()
    out, offset = [], 0
    for length in lengths:
        out.append(Subset(dataset, perm[offset:offset + length]))
        offset += length
    return out


class Sampler:
    """reference: paddle.io.Sampler."""

    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """reference: paddle.io.BatchSampler."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """reference: paddle.io.DistributedBatchSampler — shards indices per rank.

    On TPU SPMD the common path shards the *global batch array* instead, but
    the per-rank sampler is kept for multi-host input pipelines.
    """

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        from ..distributed import get_world_size, get_rank
        self.nranks = num_replicas if num_replicas is not None else \
            get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """reference: python/paddle/io/dataloader/collate.py."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return to_tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return to_tensor(np.stack([np.asarray(s._data) for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return to_tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return to_tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (str, bytes)):
        return list(batch)
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    if isinstance(sample, (tuple, list)):
        return [default_collate_fn(list(items)) for items in zip(*batch)]
    return to_tensor(np.asarray(batch))


class _PrefetchIter:
    """Background-thread prefetcher (host-side pipeline overlap)."""

    def __init__(self, producer, depth):
        self._q = queue.Queue(maxsize=depth)
        self._done = object()
        self._exc = None

        def run():
            try:
                for item in producer:
                    self._q.put(item)
            except BaseException as e:  # propagate into consumer
                self._exc = e
            finally:
                self._q.put(self._done)
        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if item is self._done:
            if self._exc is not None:
                raise self._exc
            raise StopIteration
        return item


class DataLoader:
    """reference: paddle.io.DataLoader (reader.py:262).

    num_workers>0 uses multiprocessing workers feeding an index queue
    (reference: io/dataloader/worker.py); prefetch_factor batches are staged
    ahead on a background thread either way.
    """

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self._iterable = isinstance(dataset, IterableDataset)
        if self._iterable:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable:
            raise TypeError("IterableDataset has no len()")
        if self.batch_sampler is None:
            return len(self.dataset)
        return len(self.batch_sampler)

    def _produce(self):
        if self._iterable:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield self.dataset[i]
            return
        if self.num_workers > 0:
            yield from self._produce_mp()
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _produce_mp(self):
        import multiprocessing as mp
        ctx = mp.get_context("fork")
        index_q = ctx.Queue()
        out_q = ctx.Queue(maxsize=self.num_workers * self.prefetch_factor)

        def worker_loop(wid):
            if self.worker_init_fn is not None:
                self.worker_init_fn(wid)
            while True:
                item = index_q.get()
                if item is None:
                    break
                seq, indices = item
                try:
                    batch = self.collate_fn(
                        [self.dataset[i] for i in indices])
                    # Tensors don't pickle across processes cheaply; send numpy
                    batch = _to_numpy_batch(batch)
                    out_q.put((seq, batch, None))
                except Exception as e:  # noqa: BLE001
                    out_q.put((seq, None, e))

        workers = [ctx.Process(target=worker_loop, args=(w,), daemon=True)
                   for w in range(self.num_workers)]
        for w in workers:
            w.start()
        batches = list(self.batch_sampler)
        for seq, indices in enumerate(batches):
            index_q.put((seq, indices))
        for _ in workers:
            index_q.put(None)
        pending = {}
        next_seq = 0
        received = 0
        try:
            while received < len(batches):
                seq, batch, err = out_q.get()
                received += 1
                if err is not None:
                    raise err
                pending[seq] = batch
                while next_seq in pending:
                    yield _from_numpy_batch(pending.pop(next_seq))
                    next_seq += 1
        finally:
            for w in workers:
                w.terminate()

    def __iter__(self):
        return _PrefetchIter(self._produce(), self.prefetch_factor)


def _to_numpy_batch(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj._data)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_batch(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_batch(v) for k, v in obj.items()}
    return obj


def _from_numpy_batch(obj):
    if isinstance(obj, np.ndarray):
        return to_tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_numpy_batch(o) for o in obj)
    if isinstance(obj, dict):
        return {k: _from_numpy_batch(v) for k, v in obj.items()}
    return obj


def get_worker_info():
    return None
