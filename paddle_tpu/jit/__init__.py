"""jit: whole-graph compilation (to_static) + save/load.

Capability parity: python/paddle/jit/ in the reference — @to_static
(api.py:197), SOT bytecode capture (sot/), dy2static AST path, jit.save/load
(api.py:955).

TPU-native design (SURVEY §7 mapping): instead of a CPython eval-frame hook +
bytecode simulation (reference: pybind/sot/eval_frame.c:436,
opcode_executor.py:320), capture is *trace-based*: the user function runs once
under jax.jit tracing with the tape disabled; every eager op dispatches on
tracers, producing one XLA program.  Parameters and buffers are hoisted to
inputs (functionalization), RNG is threaded as an explicit key input so
dropout differs per step, and the compiled callable is recorded on the
autograd tape as a single op — grad-of-jit stays jit, so backward is one
compiled program too.  Python control flow is evaluated at trace time
(guards = input shapes/dtypes/treedef; shape changes retrace, the reference's
bucketing concern maps to XLA's shape-keyed compile cache).
"""
from __future__ import annotations

import functools
import os
import pickle
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
import jax.tree_util as jtu

from ..framework.dispatch import call_op
from ..framework.tensor import Tensor, Parameter, wrap_array
from ..framework.tape import no_grad, is_grad_enabled
from ..framework import random as _random
from ..framework import dtype as dtypes


class InputSpec:
    """reference: paddle.static.InputSpec."""

    def __init__(self, shape, dtype="float32", name=None, stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype})"


def _is_tensor(x):
    return isinstance(x, Tensor)


class _TraceKeyProvider:
    """Deterministic per-trace key splitter fed by an input key (keeps dropout
    fresh per call under jit)."""

    def __init__(self, base_key):
        self.base = base_key
        self.count = 0

    def split_key(self):
        self.count += 1
        return jax.random.fold_in(self.base, self.count)


class StaticFunction:
    """The compiled callable produced by to_static
    (reference: dy2static/program_translator.py StaticFunction)."""

    def __init__(self, function, input_spec=None, build_strategy=None,
                 backend=None, full_graph=True):
        self._orig_fn = function
        self._layer = getattr(function, "__self__", None)
        self._input_spec = input_spec
        self._graph_broken = False
        self._jitted = None
        self._n_params = 0
        self._param_tensors: List[Tensor] = []
        self._donate = False
        functools.update_wrapper(self, function,
                                 assigned=("__name__", "__doc__",
                                           "__qualname__"), updated=())

    # -- collect layers reachable from the function (self for bound methods)
    def _collect_params(self) -> List[Tensor]:
        from ..nn.layer.layers import Layer
        owners = []
        if self._layer is not None and isinstance(self._layer, Layer):
            owners.append(self._layer)
        fn = self._orig_fn
        for cell in (getattr(fn, "__closure__", None) or ()):
            try:
                if isinstance(cell.cell_contents, Layer):
                    owners.append(cell.cell_contents)
            except ValueError:
                pass
        # plain functions referencing module-level Layers (guards the common
        # `model = ...; to_static(lambda x: model(x))` pattern)
        code = getattr(fn, "__code__", None)
        globs = getattr(fn, "__globals__", {})
        if code is not None:
            for name in code.co_names:
                obj = globs.get(name)
                if isinstance(obj, Layer):
                    owners.append(obj)
        tensors = []
        seen = set()
        for owner in owners:
            for _, p in owner.named_parameters():
                if id(p) not in seen:
                    seen.add(id(p))
                    tensors.append(p)
            for _, b in owner.named_buffers():
                if id(b) not in seen:
                    seen.add(id(b))
                    tensors.append(b)
        return tensors

    def _build(self):
        from .. import monitor
        monitor.install_compile_hooks()   # jit_recompile_count telemetry
        self._param_tensors = self._collect_params()

        def traced(param_arrays, rng_key, args_leaves, treedef):
            # swap live parameter payloads for tracers, run the python fn
            saved = [t._data for t in self._param_tensors]
            saved_provider = _random._default_generator
            try:
                for t, a in zip(self._param_tensors, param_arrays):
                    t._data = a
                _random._default_generator = _TraceKeyProvider(rng_key)
                wrapped = [wrap_array(a) if isinstance(a, jax.Array) or
                           hasattr(a, "aval") else a for a in args_leaves]
                args, kwargs = jtu.tree_unflatten(treedef, wrapped)
                with no_grad():
                    out = self._orig_fn(*args, **kwargs)
                flat_out, out_tree = jtu.tree_flatten(
                    out, is_leaf=_is_tensor)
                arrays = [o._data if _is_tensor(o) else o for o in flat_out]
                return arrays, out_tree
            finally:
                for t, a in zip(self._param_tensors, saved):
                    t._data = a
                _random._default_generator = saved_provider

        out_tree_store = {}
        owner = self

        @functools.partial(jax.jit, static_argnums=(3,))
        def jitted(param_arrays, rng_key, args_leaves, treedef):
            arrays, out_tree = traced(param_arrays, rng_key, args_leaves,
                                      treedef)
            out_tree_store[owner._current_key] = out_tree
            return tuple(arrays)

        self._jitted = jitted
        self._traced = traced             # raw trace fn for .audit()
        self._out_tree_store = out_tree_store

    def __call__(self, *args, **kwargs):
        # graph-break fallback (reference: SOT's graceful fallback,
        # jit/sot/opcode_translator/executor/opcode_executor.py:1865): when
        # the function's Python control flow needs concrete values, run it
        # eagerly instead of failing.  The decision is cached PER INSTANCE
        # (two instances of one Layer class may differ in whether their
        # config trips the break — a shared code-object cache would strip
        # compilation from the clean instance too).
        if not _TO_STATIC_ENABLED or self._graph_broken or \
                getattr(self._orig_fn, "_not_to_static", False):
            return self._orig_fn(*args, **kwargs)
        try:
            return self._call_compiled(*args, **kwargs)
        except (jax.errors.ConcretizationTypeError,
                jax.errors.TracerArrayConversionError,
                jax.errors.TracerIntegerConversionError,
                jax.errors.NonConcreteBooleanIndexError) as e:
            self._graph_broken = True
            import warnings
            code = getattr(self._orig_fn, "__code__", None)
            warn_key = code if code is not None else id(self)
            if warn_key not in _GRAPH_BREAK_WARNED:
                _GRAPH_BREAK_WARNED.add(warn_key)
                name = getattr(self._orig_fn, "__qualname__", "<fn>")
                warnings.warn(
                    f"to_static: {name} needs concrete tensor values for "
                    f"Python control flow and cannot be captured in one "
                    f"graph ({type(e).__name__}); falling back to eager "
                    f"execution for this function from now on.  Note the "
                    f"body partially ran once during the failed capture — "
                    f"Python side effects before the break happened twice "
                    f"on this call.  Use lax-style ops (paddle.where, "
                    f"masking) to keep it compiled.",
                    stacklevel=2)
            return self._orig_fn(*args, **kwargs)

    def _call_compiled(self, *args, **kwargs):
        if self._jitted is None:
            self._build()
        leaves, treedef = jtu.tree_flatten((args, kwargs), is_leaf=_is_tensor)
        tensor_leaves = [l for l in leaves if _is_tensor(l)]
        # guards: structure + tensor shapes/dtypes (shape change => retrace)
        self._current_key = (treedef,
                             tuple((tuple(t.shape), str(t.dtype))
                                   for t in tensor_leaves))
        rng_key = _random.split_key()

        jitted = self._jitted
        store = self._out_tree_store
        params = self._param_tensors

        def compiled_fn(param_arrays, input_arrays, key):
            new_leaves = []
            it = iter(input_arrays)
            for l in leaves:
                new_leaves.append(next(it) if _is_tensor(l) else l)
            return jitted(param_arrays, key, new_leaves, treedef)

        out = call_op(getattr(self._orig_fn, "__name__", "to_static"),
                      compiled_fn, (params, tensor_leaves, rng_key), {})
        out_tree = store.get(self._current_key)
        if out_tree is not None:
            return jtu.tree_unflatten(out_tree, list(out))
        return out

    def audit(self, *args, **kwargs):
        """Static-analysis view of this function: traces it exactly as
        the compiled path would (params hoisted to inputs, RNG keyed)
        and runs the ``paddle_tpu.analysis`` program auditor over the
        jaxpr.  Accepts the same example args a call would; no device
        work happens and nothing is compiled."""
        from .. import analysis
        if self._jitted is None:
            self._build()
        leaves, treedef = jtu.tree_flatten((args, kwargs),
                                           is_leaf=_is_tensor)
        tensor_leaves = [l for l in leaves if _is_tensor(l)]
        traced = self._traced

        def fn(param_arrays, input_arrays, rng_key):
            it = iter(input_arrays)
            new_leaves = [next(it) if _is_tensor(l) else l for l in leaves]
            arrays, _ = traced(param_arrays, rng_key, new_leaves, treedef)
            return tuple(arrays)

        return analysis.audit_callable(
            fn, [p._data for p in self._param_tensors],
            [t._data for t in tensor_leaves], jax.random.PRNGKey(0),
            name=f"to_static:{getattr(self._orig_fn, '__qualname__', '<fn>')}")

    # paddle API surface
    @property
    def forward(self):
        return self

    def concrete_program_specify_input_spec(self, *a, **k):
        return None

    def rollback(self):
        return self._orig_fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True, **kwargs):
    """reference: paddle.jit.to_static (api.py:197)."""
    def deco(fn):
        from ..nn.layer.layers import Layer
        if isinstance(fn, Layer):
            static = StaticFunction(fn.forward, input_spec, build_strategy,
                                    backend, full_graph)
            fn.forward = static
            return fn
        return StaticFunction(fn, input_spec, build_strategy, backend,
                              full_graph)
    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def enable_to_static(flag: bool = True):
    global _TO_STATIC_ENABLED
    _TO_STATIC_ENABLED = flag


_TO_STATIC_ENABLED = True
_GRAPH_BREAK_WARNED = set()   # warn-once keys (code object or instance id)


def ignore_module(modules):
    return None


# ------------------------------------------------------------- save / load
def save(layer, path, input_spec=None, **configs):
    """reference: paddle.jit.save (api.py:955).

    TPU-native export: the functionalized forward is serialized as StableHLO
    via jax.export (the analog of the reference's inference Program +
    paddle_inference_api), parameters pickled alongside:
      {path}.stablehlo  — portable compiled graph
      {path}.pdiparams  — parameter payloads
      {path}.meta       — structure metadata
    """
    from ..nn.layer.layers import Layer
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    layer.eval()
    params = dict(layer.named_parameters())
    buffers = dict(layer.named_buffers())
    all_state = {**params, **buffers}
    names = list(all_state)
    arrays = [all_state[n]._data for n in names]

    if input_spec is None:
        raise ValueError("input_spec is required for jit.save")
    # Dynamic dims (None/-1) become jax.export symbolic dimensions, so the
    # saved artifact serves any batch/sequence size (the reference's
    # inference program is shape-polymorphic too; the TPU runtime compiles
    # per concrete shape on first call and caches).
    n_dynamic = sum(
        sum(1 for s in spec.shape if s is None or (isinstance(s, int) and s < 0))
        for spec in input_spec if isinstance(spec, InputSpec))
    # all symbols must share one SymbolicScope, so mint them in a single call
    syms = (list(jax.export.symbolic_shape(
        ",".join(f"_d{i}" for i in range(n_dynamic))))
        if n_dynamic else [])
    input_names = []
    spec_args = []
    n_sym = 0
    for i, spec in enumerate(input_spec):
        if isinstance(spec, InputSpec):
            input_names.append(spec.name or f"input_{i}")
            shape = []
            for s in spec.shape:
                if s is None or (isinstance(s, int) and s < 0):
                    shape.append(syms[n_sym])
                    n_sym += 1
                else:
                    shape.append(int(s))
            spec_args.append(jax.ShapeDtypeStruct(tuple(shape), spec.dtype))
        elif isinstance(spec, Tensor):
            input_names.append(getattr(spec, "name", None) or f"input_{i}")
            spec_args.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                                  spec.dtype))
        else:
            raise TypeError(f"unsupported input spec {spec}")

    def infer(param_arrays, *inputs):
        saved = [all_state[n]._data for n in names]
        try:
            for n, a in zip(names, param_arrays):
                all_state[n]._data = a
            with no_grad():
                out = layer(*[wrap_array(x) for x in inputs])
            flat, _ = jtu.tree_flatten(out, is_leaf=_is_tensor)
            return tuple(o._data if _is_tensor(o) else o for o in flat)
        finally:
            for n, a in zip(names, saved):
                all_state[n]._data = a

    exported = jax.export.export(jax.jit(infer))(
        [jax.ShapeDtypeStruct(tuple(a.shape), a.dtype) for a in arrays],
        *spec_args)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".stablehlo", "wb") as f:
        f.write(exported.serialize())
    with open(path + ".pdiparams", "wb") as f:
        pickle.dump({n: np.asarray(a) for n, a in zip(names, arrays)}, f,
                    protocol=4)
    with open(path + ".meta", "wb") as f:
        pickle.dump({"param_names": names,
                     "input_names": input_names,
                     "n_outputs": len(exported.out_avals),
                     "input_specs": [(tuple(str(d) for d in s.shape),
                                      str(s.dtype)) for s in spec_args]}, f)


class TranslatedLayer:
    """reference: paddle.jit.TranslatedLayer — loaded inference function."""

    def __init__(self, exported, params, names):
        self._exported = exported
        self._params = params
        self._names = names

    def __call__(self, *inputs):
        arrays = [self._params[n] for n in self._names]
        raw = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
               for x in inputs]
        out = self._exported.call(arrays, *raw)
        outs = [wrap_array(o) for o in out]
        return outs[0] if len(outs) == 1 else outs

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("loaded inference program is eval-only "
                           "(reference: TranslatedLayer train unsupported)")


def load(path, **configs):
    """reference: paddle.jit.load."""
    with open(path + ".stablehlo", "rb") as f:
        exported = jax.export.deserialize(f.read())
    with open(path + ".pdiparams", "rb") as f:
        params = {n: jnp.asarray(a) for n, a in pickle.load(f).items()}
    with open(path + ".meta", "rb") as f:
        meta = pickle.load(f)
    return TranslatedLayer(exported, params, meta["param_names"])


from .train_step import TrainStep  # noqa: E402  (whole-step compilation)


# --------------------------------------------------- debugging verbosity
_code_level = 0
_verbosity = 0


def set_code_level(level=100, also_to_stdout=False):
    """reference: jit.set_code_level — dump transformed code at this
    level.  Trace-based to_static has no bytecode rewrite stages; level>0
    prints the traced jaxpr of each newly compiled function."""
    global _code_level
    _code_level = level


def set_verbosity(level=0, also_to_stdout=False):
    """reference: jit.set_verbosity — dy2static logging verbosity."""
    global _verbosity
    _verbosity = level
