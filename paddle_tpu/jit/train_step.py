"""Whole-train-step compilation: forward + loss + backward + optimizer
update traced into ONE XLA program.

This is the executor role of the reference's graph engines for the training
loop (reference: new executor paddle/fluid/framework/new_executor/, CUDA-graph
capture python/paddle/device/cuda/graphs.py) done the TPU-native way: trace
once, let XLA fuse the whole step, donate the parameter/optimizer buffers so
updates are in-place in HBM.

Eager ``loss.backward(); opt.step()`` dispatches hundreds of small device
programs per step; ``TrainStep`` turns the same user code (model, loss,
optimizer objects) into a single fused program — the difference is the
headline perf gap on TPU.

Usage::

    step = TrainStep(model, loss_fn, optimizer)      # loss_fn(out, *labels)
    loss = step(inputs, labels)                      # one fused XLA call
    ...
    step.sync()   # write updated arrays back into model/optimizer objects
"""
from __future__ import annotations

import contextlib
from typing import Any, Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import jax.tree_util as jtu

from .. import monitor
from ..framework.tape import no_grad
from ..framework.tensor import Tensor, wrap_array

# training-hot-path telemetry (ISSUE 5): elements of the first input
# leaf consumed per step — for (batch, seq) token-id inputs this IS the
# token count tools/train_bench.py quotes as train_tokens_total
_train_tokens = monitor.counter(
    "train_tokens_total", "elements of the first TrainStep input leaf "
    "consumed (== tokens for (batch, seq) token-id inputs)")


def _to_array(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _keep(arr):
    """An array's NamedSharding, or None (single-device / no placement)."""
    from jax.sharding import NamedSharding
    sh = getattr(arr, "sharding", None)
    return sh if isinstance(sh, NamedSharding) else None


def _is_offloaded(sh):
    from ..framework.jax_compat import is_compute_memory
    return sh is not None and \
        not is_compute_memory(getattr(sh, "memory_kind", None))


def _pin(x, sh):
    """Constrain an in-program value to its initial placement; offloaded
    (host-memory) state returns home via a real transfer."""
    if x is None or sh is None:
        return x
    if _is_offloaded(sh):
        return jax.device_put(x, sh)
    return jax.lax.with_sharding_constraint(x, sh)


def _to_compute(x, sh):
    """Stream an offloaded operand into device memory for the update."""
    if x is None or not _is_offloaded(sh):
        return x
    return jax.device_put(x, _compat_device_kind(sh))


def _compat_device_kind(sh):
    from ..framework.jax_compat import to_memory_kind
    return to_memory_kind(sh, "device")


def _device_kind(sh):
    """The device-memory variant of a sharding (grads never offload —
    they are consumed immediately by the fused update)."""
    if _is_offloaded(sh):
        return _compat_device_kind(sh)
    return sh


def _copy(arr):
    """jnp.copy drops a non-default memory kind; restore it so offloaded
    optimizer state stays in host memory."""
    if arr is None:
        return None
    out = jnp.copy(arr)
    sh = _keep(arr)
    if _is_offloaded(sh):
        out = jax.device_put(out, sh)
    return out


class TrainStep:
    """Compile model+loss+optimizer into a single donated-buffer XLA step.

    Parameters live as functional state inside the TrainStep between calls
    (the Tensor objects in ``model`` keep their stale pre-training values
    until ``sync()``); optimizer slot state is threaded the same way.
    ``amp_level``/``amp_dtype`` wrap the forward in ``amp.auto_cast``.
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 amp_level: str = "O0", amp_dtype: str = "bfloat16",
                 accumulate_steps: int = 1, accumulate_avg: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.amp_level = amp_level
        self.amp_dtype = amp_dtype
        # gradient accumulation (reference: gradient_merge pass /
        # accumulate_steps): grads sum across k calls; the optimizer
        # update applies on every k-th call via lax.cond INSIDE the
        # compiled program — one executable, no per-branch recompiles
        self.accumulate_steps = int(accumulate_steps)
        # reference gradient_merge 'avg' knob: True -> mean of the k
        # micro-grads, False -> their sum
        self.accumulate_avg = bool(accumulate_avg)
        if self.accumulate_steps < 1:
            raise ValueError(
                f"accumulate_steps (gradient_merge k_steps) must be >= 1, "
                f"got {accumulate_steps}")

        all_params = list(model.parameters())
        self._train_params = [p for p in all_params
                              if getattr(p, "trainable", True)]
        self._frozen_params = [p for p in all_params
                               if not getattr(p, "trainable", True)]
        opt = optimizer
        opt._ensure_state(self._train_params)
        # copies, not references: the compiled step donates these buffers,
        # and donating the model's/optimizer's own arrays would leave them
        # holding deleted buffers until sync()
        self._arrays = [jnp.copy(p._data) for p in self._train_params]
        self._states = {s: [_copy(opt._accumulators[s][id(p)])
                            for p in self._train_params]
                        for s in opt._state_slots}
        self._masters = [_copy(opt._master_weights.get(id(p)))
                         for p in self._train_params]
        self._update_fn = opt._functional_update_fn(self._train_params)
        # accumulate in fp32 whenever a master weight exists: summing k
        # bf16 micro-grads in bf16 rounds away exactly the small terms
        # the master-weight machinery protects.  Accumulators always live
        # in DEVICE memory (they're touched every micro-step) even when
        # the master they mirror is host-offloaded.
        def _accum_init(a, m):
            src = m if m is not None else a
            z = jnp.zeros_like(src)
            sh = _keep(src)
            if _is_offloaded(sh):
                z = jax.device_put(z, _compat_device_kind(sh))
            return z

        self._grad_accum = [
            _accum_init(a, m)
            for a, m in zip(self._arrays, self._masters)] \
            if self.accumulate_steps > 1 else []
        self._micro_step = 0
        self._compiled = None
        self._compiled_scan = None
        self._scan_fn = None
        self._last_loss = None

    # ------------------------------------------------------------------ build
    def _compute_placements(self):
        """Record every operand's home placement ONCE (params, optimizer
        state, masters, gradients) — shared by the single-step program
        and the K-step fused scan so their pinning cannot diverge."""
        param_shardings = [_keep(a) for a in self._arrays]
        state_shardings = {k: [_keep(a) for a in v]
                           for k, v in self._states.items()}
        master_shardings = [_keep(m) for m in self._masters]
        # ZeRO offload mode: on TPU the host-resident state stays
        # pinned_host ACROSS the program boundary (streamed in/out inside
        # the compiled step — overlappable transfers).  Other backends
        # (CPU tests) can't compile mixed-memory donated programs, so the
        # state is staged eagerly around the call instead — the same
        # semantics the reference's cpu_offload staging has
        # (group_sharded_stage3.py:85); host==device memory there anyway.
        offloaded = (any(_is_offloaded(s)
                         for v in state_shardings.values() for s in v)
                     or any(_is_offloaded(s) for s in master_shardings))
        self._offload_boundary = offloaded and \
            jax.default_backend() != "tpu"
        if self._offload_boundary:
            self._state_homes = (state_shardings, master_shardings)
            state_shardings = {k: [_device_kind(s) for s in v]
                               for k, v in state_shardings.items()}
            master_shardings = [_device_kind(s) for s in master_shardings]
        else:
            self._state_homes = None
        # grad placement follows the param's sharded state (or master) —
        # the gradient's consumer
        grad_shardings = []
        for i in range(len(self._arrays)):
            sh = next((state_shardings[k][i] for k in self._states
                       if state_shardings[k][i] is not None), None)
            grad_shardings.append(_device_kind(sh or master_shardings[i]))
        self._placements = (param_shardings, state_shardings,
                            master_shardings, grad_shardings)

    def _make_inner(self):
        """The pure single-micro-step function (forward + loss + backward
        + conditional optimizer apply).  ONE definition serves both the
        single-step jit and the body of the K-step ``lax.scan`` — the
        fused path cannot drift numerically from the escape hatch."""
        model = self.model
        loss_fn = self.loss_fn
        opt = self.optimizer
        train_params = self._train_params
        frozen_params = self._frozen_params
        update_fn = self._update_fn
        grad_clip = opt._grad_clip
        (param_shardings, state_shardings, master_shardings,
         grad_shardings) = self._placements

        if self.amp_level and self.amp_level != "O0":
            from .. import amp

            def cast_ctx():
                return amp.auto_cast(level=self.amp_level,
                                     dtype=self.amp_dtype)
        else:
            def cast_ctx():
                return contextlib.nullcontext()

        K = self.accumulate_steps

        def pure_step(arrays, states, masters, accum, frozen, lr, stepno,
                      apply_flag, in_leaves, label_leaves, treedefs):
            in_tree, label_tree = treedefs
            # ZeRO offload: stream host-resident optimizer state into
            # device memory for the fused update (returned home by _pin)
            states = {k: [_to_compute(a, s)
                          for a, s in zip(states[k], state_shardings[k])]
                      for k in states}
            masters = [_to_compute(m, s)
                       for m, s in zip(masters, master_shardings)]

            def loss_of(arrs):
                saved = [p._data for p in train_params]
                saved_frozen = [p._data for p in frozen_params]
                try:
                    for p, a in zip(train_params, arrs):
                        p._data = a
                    for p, a in zip(frozen_params, frozen):
                        p._data = a
                    inputs = jtu.tree_unflatten(
                        in_tree, [wrap_array(a) for a in in_leaves])
                    labels = jtu.tree_unflatten(
                        label_tree, [wrap_array(a) for a in label_leaves])
                    with no_grad(), cast_ctx():
                        outputs = model(*inputs)
                    outs = outputs if isinstance(outputs, (list, tuple)) \
                        else (outputs,)
                    loss = loss_fn(outputs, *labels)
                    out_arrays = [o._data for o in outs
                                  if isinstance(o, Tensor)]
                    return loss._data.astype(jnp.float32), out_arrays
                finally:
                    for p, s in zip(train_params, saved):
                        p._data = s
                    for p, s in zip(frozen_params, saved_frozen):
                        p._data = s

            (loss, outs), grads = jax.value_and_grad(
                loss_of, has_aux=True)(arrays)
            # ZeRO stage-2/3 gradient placement: when a param's optimizer
            # state is sharded, land its gradient with the SAME sharding
            # (XLA lowers the grad psum to reduce-scatter — the pattern the
            # reference's stage-2 implements by hand,
            # group_sharded_optimizer_stage2.py:53).  Derived from the
            # state shardings so any shard_optimizer user gets it; a
            # group_sharded level of 'os' (stage-1) opts out — full grads
            # are that stage's definition.
            if getattr(opt, "_sharding_level", None) != "os":
                grads = [_pin(g, s) for g, s in zip(grads, grad_shardings)]

            def apply_clip(gs):
                if grad_clip is None:
                    return gs
                # real Parameter objects, not bare wraps: the clip consults
                # per-param flags (need_clip) that live on the Parameter
                pairs = [(p, wrap_array(g))
                         for p, g in zip(train_params, gs)]
                with no_grad():
                    clipped = grad_clip(pairs)
                return [g._data for _, g in clipped]

            if K == 1:
                grads = apply_clip(grads)
                new_arrays, new_states, new_masters = update_fn(
                    lr, stepno, arrays, grads, states, masters)
                new_accum = accum
            else:
                # accumulate; the k-th call applies the averaged update and
                # resets the accumulators — both arms of ONE compiled cond
                summed = [a + g for a, g in zip(accum, grads)]

                def do_update(operand):
                    arrays_, states_, masters_, summed_ = operand
                    # back to the grad dtype the update rule expects (the
                    # K=1 path feeds raw param-dtype grads)
                    denom = K if self.accumulate_avg else 1
                    avg = apply_clip([(g / denom).astype(a.dtype)
                                      for g, a in zip(summed_, arrays_)])
                    na, ns, nm = update_fn(lr, stepno, arrays_, avg,
                                           states_, masters_)
                    return na, ns, nm, [jnp.zeros_like(g) for g in summed_]

                def skip_update(operand):
                    arrays_, states_, masters_, summed_ = operand
                    return arrays_, states_, masters_, summed_

                new_arrays, new_states, new_masters, new_accum = \
                    jax.lax.cond(apply_flag, do_update, skip_update,
                                 (arrays, states, masters, summed))
            # pin outputs to their INITIAL placements: donated-buffer steps
            # otherwise drift to whatever GSPMD chose (e.g. ZeRO-1 params
            # silently becoming sharded after one step, erasing the
            # stage-1/2 vs stage-3 distinction and surprising eager readers)
            new_arrays = [_pin(a, s)
                          for a, s in zip(new_arrays, param_shardings)]
            new_states = {k: [_pin(a, s) for a, s in
                              zip(new_states[k], state_shardings[k])]
                          for k in new_states}
            new_masters = [_pin(a, s)
                           for a, s in zip(new_masters, master_shardings)]
            # accumulators follow the gradient placement (same reason as
            # the pins above: donated-buffer steps must not drift
            # shardings between calls, which would recompile every step)
            new_accum = [_pin(a, s)
                         for a, s in zip(new_accum, grad_shardings)]
            return (loss, outs, new_arrays, new_states, new_masters,
                    new_accum)

        return pure_step

    def _build(self):
        self._compute_placements()
        self._inner = self._make_inner()
        self._compiled = jax.jit(self._inner, donate_argnums=(0, 1, 2, 3),
                                 static_argnums=(10,))

    # ------------------------------------------------------------------- call
    def _prepare_args(self, inputs, labels):
        """Flatten user inputs/labels the way the compiled step expects —
        shared by __call__ and memory_analysis so their signatures cannot
        diverge."""
        if self._compiled is None:
            self._build()
        if not isinstance(inputs, (list, tuple)):
            inputs = (inputs,)
        if not isinstance(labels, (list, tuple)):
            labels = (labels,)
        in_leaves, in_tree = jtu.tree_flatten(
            inputs, is_leaf=lambda x: isinstance(x, Tensor))
        label_leaves, label_tree = jtu.tree_flatten(
            labels, is_leaf=lambda x: isinstance(x, Tensor))
        in_leaves = [_to_array(x) for x in in_leaves]
        label_leaves = [_to_array(x) for x in label_leaves]
        frozen = [p._data for p in self._frozen_params]
        return in_leaves, label_leaves, (in_tree, label_tree), frozen

    def _stage_in(self):
        """Boundary-mode offload: transfer host-resident state into device
        memory for the compiled call (no-op in program mode)."""
        if not getattr(self, "_offload_boundary", False):
            return self._states, self._masters
        homes_s, homes_m = self._state_homes
        states = {k: [jax.device_put(a, _device_kind(s))
                      if _is_offloaded(s) else a
                      for a, s in zip(self._states[k], homes_s[k])]
                  for k in self._states}
        masters = [jax.device_put(m, _device_kind(s))
                   if m is not None and _is_offloaded(s) else m
                   for m, s in zip(self._masters, homes_m)]
        return states, masters

    def _stage_out(self):
        """Boundary-mode offload: return the fresh state home to host
        memory after the compiled call."""
        if not getattr(self, "_offload_boundary", False):
            return
        homes_s, homes_m = self._state_homes
        self._states = {k: [jax.device_put(a, s)
                            if _is_offloaded(s) else a
                            for a, s in zip(self._states[k], homes_s[k])]
                        for k in self._states}
        self._masters = [jax.device_put(m, s)
                         if m is not None and _is_offloaded(s) else m
                         for m, s in zip(self._masters, homes_m)]

    def __call__(self, inputs, labels=()):
        """One fused train step.  ``inputs``/``labels`` are a Tensor/array or
        (possibly nested) tuple/list of them; returns the scalar loss Tensor
        (device value — no host sync unless you read it)."""
        in_leaves, label_leaves, treedefs, frozen = self._prepare_args(
            inputs, labels)

        opt = self.optimizer
        K = self.accumulate_steps
        self._micro_step += 1
        apply_now = (self._micro_step % K == 0)
        if apply_now:
            # the optimizer's schedule advances once per APPLIED update
            opt._global_step += 1
        lr = jnp.asarray(opt.get_lr(), jnp.float32)
        stepno = jnp.asarray(opt._global_step, jnp.int32)

        # signature only (no arrays pinned): lets program_text() lower the
        # compiled step later without holding batch data alive; shardings
        # ride along so the lowered text matches the executed partitioning
        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=_keep(a))

        self._last_sig = ([sds(a) for a in in_leaves],
                          [sds(a) for a in label_leaves], treedefs)
        states, masters = self._stage_in()
        (loss, outs, self._arrays, self._states, self._masters,
         self._grad_accum) = self._compiled(
            self._arrays, states, masters, self._grad_accum,
            frozen, lr, stepno, jnp.asarray(apply_now), in_leaves,
            label_leaves, treedefs)
        self._stage_out()
        if in_leaves:
            _train_tokens.inc(in_leaves[0].size)
        self._last_outputs = [wrap_array(o) for o in outs]
        self._last_loss = wrap_array(loss)
        return self._last_loss

    # ------------------------------------------------------- K-step fusion
    def _sched(self):
        """The optimizer's LRScheduler instance, or None for a plain
        float learning rate."""
        from ..optimizer.lr import LRScheduler
        lr = self.optimizer._learning_rate
        return lr if isinstance(lr, LRScheduler) else None

    def _sched_fingerprint(self):
        """Identity + hyperparameters of the current schedule, NESTED
        schedules included (LinearWarmup wraps another LRScheduler).
        The traced fn closes over the hyperparams as Python constants,
        so the cache (and the compiled scan) must be invalidated not
        just when the schedule OBJECT is swapped but also when it (or
        its inner schedule) is mutated in place — e.g. a checkpoint
        restore through ``Optimizer.set_state_dict`` rewriting
        ``base_lr``/``gamma`` on the same object.  ``last_epoch``/
        ``last_lr`` are excluded: they advance every step and are
        operands, not baked constants."""
        from ..optimizer.lr import LRScheduler

        def fp(sched):
            hyper = tuple(sorted(
                (k, repr(v)) for k, v in sched.state_dict().items()
                if k not in ("last_epoch", "last_lr")))
            nested = tuple(sorted(
                (k, fp(v)) for k, v in vars(sched).items()
                if isinstance(v, LRScheduler)))
            return (id(sched), hyper, nested)

        sched = self._sched()
        return None if sched is None else fp(sched)

    def _traced_sched_fn(self):
        """Memoized traced LR schedule (``step -> f32``), validated by
        abstract tracing; None when the schedule concretizes — the
        auto-detected signal to take the single-step escape hatch."""
        key = self._sched_fingerprint()
        cached = getattr(self, "_sched_fn_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        fn = None
        get = getattr(self.optimizer, "_traced_schedule", None)
        cand = get() if get is not None else None
        if cand is not None:
            try:
                jax.eval_shape(
                    lambda s: jnp.asarray(cand(s), jnp.float32),
                    jax.ShapeDtypeStruct((), jnp.int32))
                fn = cand
            except Exception:   # noqa: BLE001 — untraceable schedule
                fn = None
        self._sched_fn_cache = (key, fn)
        return fn

    @property
    def fused_supported(self) -> bool:
        """True when ``run_steps`` compiles ONE lax.scan dispatch for
        all k micro-steps (constant lr, or a schedule whose
        ``traced_lr`` validated); False means the schedule cannot be
        traced and run_steps falls back to k single-step dispatches."""
        if self._sched() is None:
            return True
        return self._traced_sched_fn() is not None

    def _build_scan(self):
        if self._compiled is None:
            self._build()
        inner = self._inner
        K = self.accumulate_steps
        sched_fn = self._traced_sched_fn()

        def scan_steps(arrays, states, masters, accum, frozen, micro0,
                       g0, sched0, lr_op, lr_factor, in_stacks,
                       label_stacks, treedefs):
            k = (in_stacks if in_stacks else label_stacks)[0].shape[0]

            def body(carry, xs):
                arrays, states, masters, accum = carry
                i, in_leaves, label_leaves = xs
                micro = micro0 + i + 1
                apply_flag = (micro % K) == 0
                # the schedule step counter advances once per MICRO
                # step (the hapi per-batch LRScheduler-callback
                # cadence); the optimizer step counter (adam bias
                # correction) once per APPLIED update
                stepno = (g0 + micro // K - micro0 // K).astype(jnp.int32)
                if sched_fn is None:
                    lr = lr_op
                else:
                    lr = jnp.asarray(sched_fn(sched0 + i),
                                     jnp.float32) * lr_factor
                loss, _outs, arrays, states, masters, accum = inner(
                    arrays, states, masters, accum, frozen, lr, stepno,
                    apply_flag, list(in_leaves), list(label_leaves),
                    treedefs)
                return (arrays, states, masters, accum), loss

            (arrays, states, masters, accum), losses = jax.lax.scan(
                body, (arrays, states, masters, accum),
                (jnp.arange(k, dtype=jnp.int32), tuple(in_stacks),
                 tuple(label_stacks)))
            return losses, arrays, states, masters, accum

        self._scan_fn = scan_steps
        # rebuild if the schedule is swapped OR mutated in place
        self._scan_sched = self._sched_fingerprint()
        self._compiled_scan = jax.jit(
            scan_steps, donate_argnums=(0, 1, 2, 3), static_argnums=(12,))

    def _fused_batch_stacks(self, batches):
        """Flatten every ``(inputs, labels)`` pair exactly the way
        ``__call__`` does and stack the leaves on a leading k axis —
        shared by run_steps and audit_fused so their signatures cannot
        diverge."""
        per_in, per_label = [], []
        treedefs = frozen = None
        for item in batches:
            if not (isinstance(item, (tuple, list)) and len(item) == 2):
                raise ValueError(
                    "run_steps takes a sequence of (inputs, labels) "
                    "pairs, each shaped as __call__ accepts")
            in_leaves, label_leaves, td, frozen = self._prepare_args(
                item[0], item[1])
            if treedefs is None:
                treedefs = td
            elif td != treedefs:
                raise ValueError(
                    "all run_steps batches must share one input/label "
                    "structure")
            per_in.append(in_leaves)
            per_label.append(label_leaves)
        in_stacks = [jnp.stack([s[j] for s in per_in])
                     for j in range(len(per_in[0]))]
        label_stacks = [jnp.stack([s[j] for s in per_label])
                        for j in range(len(per_label[0]))]
        return in_stacks, label_stacks, treedefs, frozen

    def _fused_scalars(self):
        """The traced bookkeeping scalars of one fused dispatch (all
        operands, never baked in — their change per call must not
        recompile)."""
        opt = self.optimizer
        sched = self._sched()
        return (jnp.asarray(self._micro_step, jnp.int32),
                jnp.asarray(opt._global_step, jnp.int32),
                jnp.asarray(0 if sched is None else sched.last_epoch,
                            jnp.int32),
                jnp.asarray(opt.get_lr(), jnp.float32),
                jnp.asarray(opt._lr_factor, jnp.float32))

    def run_steps(self, batches, k=None):
        """K micro-steps in ONE device dispatch: a ``lax.scan`` over the
        stacked batches, donation threaded through the scan carry, the
        learning rate and step number computed INSIDE the program from
        the traced schedule.  Semantically equivalent to::

            for inputs, labels in batches:
                loss_i = step(inputs, labels)
                schedule.step()          # if the lr is an LRScheduler

        (an LRScheduler advances once per micro-step — the cadence
        hapi's per-batch LRScheduler callback drives).  Returns the
        per-step losses as a device-resident ``(k,)`` Tensor; nothing
        syncs to the host unless the caller reads it.

        ``batches`` is a sequence of ``(inputs, labels)`` pairs, each as
        ``__call__`` accepts, all sharing one structure/shape/dtype.
        Escape hatch (auto-detected, ``fused_supported`` False): a
        schedule whose lr cannot be traced runs the same loop as k
        single-step dispatches.

        Schedule hyperparameter changes (object swap OR in-place
        mutation, nested schedules included) rebuild the fused program
        automatically.  The fused lr is computed functionally from the
        schedule's CURRENT hyperparams; after a partial in-place edit,
        refresh the host cache too (``sched.step(sched.last_epoch)``)
        or the single-step path will read the stale ``last_lr`` for one
        step — a full checkpoint restore carries a consistent
        ``last_lr`` and needs no refresh."""
        batches = list(batches)
        if k is None:
            k = len(batches)
        if k != len(batches) or k < 1:
            raise ValueError(
                f"k ({k}) must equal the number of batches "
                f"({len(batches)}) and be >= 1")
        sched = self._sched()
        if not self.fused_supported:
            losses = []
            for inputs, labels in batches:
                losses.append(self(inputs, labels)._data)
                if sched is not None:
                    sched.step()
            return wrap_array(jnp.stack(losses))
        if self._compiled_scan is None or \
                self._scan_sched != self._sched_fingerprint():
            self._build_scan()
        in_stacks, label_stacks, treedefs, frozen = \
            self._fused_batch_stacks(batches)
        scalars = self._fused_scalars()
        states, masters = self._stage_in()
        (losses, self._arrays, self._states, self._masters,
         self._grad_accum) = self._compiled_scan(
            self._arrays, states, masters, self._grad_accum, frozen,
            *scalars, in_stacks, label_stacks, treedefs)
        self._stage_out()
        if in_stacks:
            _train_tokens.inc(in_stacks[0].size)
        # host bookkeeping mirrors what the in-program schedule already
        # computed: micro/global step counters and the scheduler state
        K = self.accumulate_steps
        micro0 = self._micro_step
        self._micro_step += k
        self.optimizer._global_step += (micro0 + k) // K - micro0 // K
        if sched is not None:
            for _ in range(k):
                sched.step()
        self._last_outputs = []
        self._last_loss = wrap_array(losses[k - 1])
        return wrap_array(losses)

    def fused_program_spec(self, batches):
        """The fused K-step program's EXACT traced function + abstract
        operand list — the shared tracing spec under :meth:`audit_fused`
        (hazard rules) and ``analysis.cost``'s FLOPs/HBM estimator
        (ISSUE 10: the train-lane MFU numerator), so both see the one
        call contract ``run_steps`` executes.  Returns ``(fn, args,
        donate_argnums, static_argnums)``; params/optimizer state ride
        as abstract avals — no device work, nothing materialized."""
        if not self.fused_supported:
            raise ValueError(
                "the LR schedule is not traceable — run_steps uses the "
                "single-step escape hatch and there is no fused program "
                "to audit")
        if self._compiled_scan is None or \
                self._scan_sched != self._sched_fingerprint():
            self._build_scan()
        # abstract stacking: only the FIRST batch's leaf shapes/dtypes
        # are read and a leading k axis prepended — no jnp.stack, no
        # device allocation for the k real batches
        batches = list(batches)
        k = len(batches)
        first = batches[0]
        if not (isinstance(first, (tuple, list)) and len(first) == 2):
            raise ValueError(
                "fused_program_spec takes the same (inputs, labels) "
                "pairs as run_steps")
        in_leaves, label_leaves, treedefs, _frozen = self._prepare_args(
            first[0], first[1])
        in_stacks = [jax.ShapeDtypeStruct((k,) + tuple(a.shape), a.dtype)
                     for a in in_leaves]
        label_stacks = [jax.ShapeDtypeStruct((k,) + tuple(a.shape),
                                             a.dtype)
                        for a in label_leaves]

        def sds(a):
            return jax.ShapeDtypeStruct(a.shape, a.dtype,
                                        sharding=_keep(a))

        def staged_sds(a):
            if a is None:
                return None
            return jax.ShapeDtypeStruct(
                a.shape, a.dtype, sharding=_device_kind(_keep(a)))

        arrays = [staged_sds(a) for a in self._arrays]
        states = {s: [staged_sds(a) for a in v]
                  for s, v in self._states.items()}
        masters = [staged_sds(m) for m in self._masters]
        accum = [staged_sds(a) for a in self._grad_accum]
        frozen = [sds(p._data) for p in self._frozen_params]
        scalars = tuple(sds(x) for x in self._fused_scalars())
        args = (arrays, states, masters, accum, frozen, *scalars,
                in_stacks, label_stacks, treedefs)
        return self._scan_fn, args, (0, 1, 2, 3), (12,)

    def audit_fused(self, batches, **limits):
        """``analysis.audit_callable`` on the fused K-step program:
        traces the EXACT operand list and donation contract run_steps
        executes (:meth:`fused_program_spec`) and returns the
        ProgramAudit.  The certification lane tools/train_bench.py
        gates on: no host callbacks, donation intact, no f32 creep.

        When the step's operands carry NamedShardings over a >1 mesh
        (DataParallel / sharded optimizer state), the tier-3 SPMD
        audit (``analysis.spmd``) runs automatically: gradient-sync
        collectives are named and priced (the HLO tier sees the
        GSPMD-inserted all-reduces no jaxpr walk can), its hazard
        findings merge into this audit, and the full distributed audit
        rides on ``audit.spmd``."""
        from ..analysis import audit_callable
        fn, args, donate, static = self.fused_program_spec(batches)
        audit = audit_callable(
            fn, *args, donate_argnums=donate, static_argnums=static,
            name="TrainStep.run_steps", **limits)
        try:
            import math as _math
            from ..analysis.spmd import (audit_spmd_fused,
                                         mesh_axes_of_args)
            axes = mesh_axes_of_args(jtu.tree_leaves(tuple(
                a for i, a in enumerate(args) if i not in static)))
            if _math.prod(axes.values() or [1]) > 1:
                audit.spmd = audit_spmd_fused(
                    self, batches, publish=limits.get("publish", True))
                audit.findings.extend(audit.spmd.findings)
        except Exception:   # noqa: BLE001 — tier 3 must never fail tier 1
            pass
        return audit

    def static_peak_hbm(self, inputs, labels=()) -> float:
        """Static peak-HBM estimate of the single-step program
        (``analysis.spmd.estimate_peak_hbm``: a buffer-lifetime walk
        honoring the step's donation contract) — the memory-gate
        pre-verdict ``bench.py`` quotes next to the measured
        ``planned_peak_bytes``, available from a trace alone: no
        compile, no device execution, so a gate-rejecting config costs
        milliseconds instead of a failed run."""
        import jax.numpy as jnp
        from ..analysis.spmd import estimate_peak_hbm
        in_leaves, label_leaves, treedefs, frozen = self._prepare_args(
            inputs, labels)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        stepno = jnp.asarray(self.optimizer._global_step + 1, jnp.int32)
        closed = jax.make_jaxpr(self._inner, static_argnums=(10,))(
            self._arrays, self._states, self._masters, self._grad_accum,
            frozen, lr, stepno, jnp.asarray(True), in_leaves,
            label_leaves, treedefs)
        donated = [a for tree in (self._arrays, self._states,
                                  self._masters, self._grad_accum)
                   for a in jtu.tree_leaves(tree)]
        return estimate_peak_hbm(closed, donated_avals=donated)

    # -------------------------------------------------------------- analysis
    def _lower(self, in_leaves, label_leaves, treedefs, as_avals=False):
        """Single lowering call site shared by memory_analysis and
        program_text, so the argument list cannot drift from the compiled
        signature.  ``as_avals=True`` lowers the params/state operands as
        ShapeDtypeStructs carrying the staged shardings — no arrays are
        materialized (in boundary-mode offload, _stage_in would otherwise
        device_put the whole host-resident state just to lower)."""
        frozen = [p._data for p in self._frozen_params]
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        stepno = jnp.asarray(self.optimizer._global_step + 1, jnp.int32)
        if as_avals:
            def staged_sds(a):
                if a is None:
                    return None
                return jax.ShapeDtypeStruct(
                    a.shape, a.dtype, sharding=_device_kind(_keep(a)))

            arrays = [staged_sds(a) for a in self._arrays]
            states = {k: [staged_sds(a) for a in v]
                      for k, v in self._states.items()}
            masters = [staged_sds(m) for m in self._masters]
            accum = [staged_sds(a) for a in self._grad_accum]
        else:
            arrays = self._arrays
            states, masters = self._stage_in()
            accum = self._grad_accum
        return self._compiled.lower(
            arrays, states, masters, accum, frozen, lr, stepno,
            jnp.asarray(True), in_leaves, label_leaves, treedefs)

    def memory_analysis(self, inputs, labels=(), return_hlo=False):
        """Per-device compiled memory profile of the whole train step
        (argument/output/temp/alias bytes) — the observability the
        reference's sharding stages expose through max_memory_allocated.
        ZeRO stage differences are visible here: stage-3 shrinks the donated
        parameter arguments, stage-2 shrinks gradient temps.

        Memoized per input-shape signature: repeat calls (periodic
        monitoring) don't pay a whole-step recompile."""
        in_leaves, label_leaves, treedefs, frozen = self._prepare_args(
            inputs, labels)
        key = (tuple((a.shape, str(a.dtype))
                     for a in in_leaves + label_leaves),
               treedefs, bool(return_hlo))
        cached = getattr(self, "_mem_cache", {}).get(key)
        if cached is not None:
            return dict(cached)
        lowered = self._lower(in_leaves, label_leaves, treedefs)
        compiled = lowered.compile()
        mem = compiled.memory_analysis()
        try:   # XLA's analytic FLOP count for the WHOLE step program —
               # the numerator of MFU (BASELINE config 5)
            flops = float((compiled.cost_analysis() or {}).get("flops", 0.0))
        except Exception:   # noqa: BLE001 — backend without cost model
            flops = 0.0
        out = {
            "flops_per_step": flops,
            "argument_bytes": getattr(mem, "argument_size_in_bytes", 0),
            "output_bytes": getattr(mem, "output_size_in_bytes", 0),
            "temp_bytes": getattr(mem, "temp_size_in_bytes", 0),
            "alias_bytes": getattr(mem, "alias_size_in_bytes", 0),
            "generated_code_bytes": getattr(
                mem, "generated_code_size_in_bytes", 0),
            # ZeRO offload moves bytes from the device columns above into
            # these host columns (populated on backends with distinct
            # host/device memories, i.e. TPU)
            "host_argument_bytes": getattr(
                mem, "host_argument_size_in_bytes", 0),
            "host_output_bytes": getattr(
                mem, "host_output_size_in_bytes", 0),
            "host_temp_bytes": getattr(mem, "host_temp_size_in_bytes", 0),
        }
        if return_hlo:
            out["hlo"] = lowered.as_text()
        if not hasattr(self, "_mem_cache"):
            self._mem_cache = {}
        self._mem_cache[key] = dict(out)
        return out

    def program_text(self) -> Optional[str]:
        """The whole-step program as StableHLO text (the TPU-native analog
        of the reference's partitioned dist_main_program) — available
        after the first call; shardings appear as sdy.sharding (Shardy)
        attributes.  Lowered from avals only (no state materialized) and
        memoized per signature."""
        sig = getattr(self, "_last_sig", None)
        if self._compiled is None or sig is None:
            return None
        in_sds, label_sds, treedefs = sig
        key = (tuple((s.shape, str(s.dtype)) for s in in_sds + label_sds),
               treedefs)
        cache = getattr(self, "_program_text_cache", None)
        if cache is not None and cache[0] == key:
            return cache[1]
        text = self._lower(in_sds, label_sds, treedefs,
                           as_avals=True).as_text()
        self._program_text_cache = (key, text)
        return text

    # ------------------------------------------------------------------- sync
    def sync(self):
        """Write the functional state back into the model Parameters and the
        optimizer's accumulators (call before checkpointing/eval)."""
        opt = self.optimizer
        for p, a in zip(self._train_params, self._arrays):
            p._data = a
        for s in opt._state_slots:
            for p, arr in zip(self._train_params, self._states[s]):
                opt._accumulators[s][id(p)] = arr
        for p, m in zip(self._train_params, self._masters):
            if m is not None:
                opt._master_weights[id(p)] = m

    @property
    def last_outputs(self) -> List[Tensor]:
        return getattr(self, "_last_outputs", [])
