"""paddle.linalg as an importable module (reference:
python/paddle/linalg.py re-exporting tensor.linalg)."""
from .tensor.linalg import *  # noqa: F401,F403
from .tensor import linalg as _impl

__all__ = [n for n in dir(_impl) if not n.startswith("_")]
