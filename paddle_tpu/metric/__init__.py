"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..framework.tensor import Tensor, to_tensor, wrap_array


class Metric:
    """reference: paddle.metric.Metric."""

    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """reference: paddle.metric.Accuracy."""

    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        idx = np.argsort(-p, axis=-1)[..., :self.maxk]
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        if l.ndim == p.ndim:  # one-hot
            l = np.argmax(l, axis=-1)
        correct = (idx == l[..., None]).astype(np.float32)
        return to_tensor(correct)

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        num = c.shape[0] if c.ndim > 0 else 1
        accs = []
        for k in self.topk:
            corr = c[..., :k].sum()
            self.total[self.topk.index(k)] += corr
            self.count[self.topk.index(k)] += num
            accs.append(corr / max(num, 1))
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pred_pos = (p.reshape(-1) > 0.5)
        actual = l.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & actual))
        self.fp += int(np.sum(pred_pos & ~actual))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        pred_pos = (p.reshape(-1) > 0.5)
        actual = l.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & actual))
        self.fn += int(np.sum(~pred_pos & actual))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """reference: paddle.metric.Auc (trapezoid over threshold buckets)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        if p.ndim == 2:
            p = p[:, 1]
        idx = np.minimum((p * self.num_thresholds).astype(np.int64),
                         self.num_thresholds)
        lbl = l.reshape(-1).astype(bool)
        np.add.at(self._stat_pos, idx[lbl], 1)
        np.add.at(self._stat_neg, idx[~lbl], 1)

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, dtype=np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, dtype=np.int64)

    def accumulate(self):
        tot_pos = self._stat_pos[::-1].cumsum()
        tot_neg = self._stat_neg[::-1].cumsum()
        tp, fp = tot_pos, tot_neg
        P, N = tot_pos[-1], tot_neg[-1]
        if P == 0 or N == 0:
            return 0.0
        tpr = tp / P
        fpr = fp / N
        return float(np.trapezoid(tpr, fpr))

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None):
    m = Accuracy(topk=(k,))
    c = m.compute(input, label)
    m.update(c)
    return to_tensor(np.asarray(m.accumulate(), dtype=np.float32))
