"""Model zoo: LLaMA (flagship), BERT; vision models in paddle_tpu.vision."""
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, llama_7b, llama_small,
    shard_llama,
)
from .llama_moe import (  # noqa: F401
    LlamaMoeConfig, LlamaMoeDecoderLayer, LlamaMoeForCausalLM,
    LlamaMoeModel, shard_llama_moe,
)
from .bert import (  # noqa: F401
    BertConfig, BertModel, BertForSequenceClassification, BertForMaskedLM,
    bert_base, bert_tiny,
)
from .crnn import CRNN, crnn_tiny  # noqa: F401
