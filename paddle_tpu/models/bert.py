"""BERT / ERNIE-class encoder (BASELINE config 2).

Capability parity: the reference fine-tunes BERT/ERNIE-3.0 via PaddleNLP on
top of paddle.nn.TransformerEncoder; this is the equivalent native stack.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ..framework.tensor import Tensor
from ..nn.layer.layers import Layer
from ..nn.layer.common import Linear, Embedding, Dropout
from ..nn.layer.norm import LayerNorm
from ..nn.layer.activation import Tanh
from ..nn.layer.transformer import TransformerEncoder, TransformerEncoderLayer
from ..nn import functional as F
from ..nn.initializer import Normal
from .. import tensor as T


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    initializer_range: float = 0.02
    layer_norm_eps: float = 1e-12
    num_labels: int = 2


def bert_base():
    return BertConfig()


def bert_tiny():
    return BertConfig(vocab_size=1000, hidden_size=128, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=512,
                      max_position_embeddings=128)


class BertEmbeddings(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        init = Normal(std=c.initializer_range)
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size,
                                         weight_attr=init)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size, weight_attr=init)
        self.token_type_embeddings = Embedding(c.type_vocab_size,
                                               c.hidden_size, weight_attr=init)
        self.layer_norm = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        s = input_ids.shape[1]
        if position_ids is None:
            position_ids = T.arange(s, dtype="int32")
        if token_type_ids is None:
            token_type_ids = T.zeros_like(input_ids)
        emb = (self.word_embeddings(input_ids)
               + self.position_embeddings(position_ids)
               + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, c: BertConfig):
        super().__init__()
        self.dense = Linear(c.hidden_size, c.hidden_size,
                            weight_attr=Normal(std=c.initializer_range))
        self.activation = Tanh()

    def forward(self, hidden):
        return self.activation(self.dense(hidden[:, 0]))


class BertModel(Layer):
    """reference capability: paddlenlp BertModel on paddle.nn primitives."""

    def __init__(self, config: Optional[BertConfig] = None):
        super().__init__()
        c = config or BertConfig()
        self.config = c
        self.embeddings = BertEmbeddings(c)
        enc_layer = TransformerEncoderLayer(
            c.hidden_size, c.num_attention_heads, c.intermediate_size,
            dropout=c.hidden_dropout_prob, activation=c.hidden_act,
            attn_dropout=c.attention_probs_dropout_prob,
            layer_norm_eps=c.layer_norm_eps)
        self.encoder = TransformerEncoder(enc_layer, c.num_hidden_layers)
        self.pooler = BertPooler(c)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                position_ids=None):
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        mask = None
        if attention_mask is not None:
            # additive mask (b, 1, 1, s)
            m = (1.0 - attention_mask.astype("float32")) * -1e9
            mask = m.reshape([m.shape[0], 1, 1, m.shape[1]])
        encoded = self.encoder(emb, mask)
        pooled = self.pooler(encoded)
        return encoded, pooled


class BertForSequenceClassification(Layer):
    """reference capability: GLUE/SST-2 fine-tune entrypoint."""

    def __init__(self, config: Optional[BertConfig] = None):
        super().__init__()
        c = config or BertConfig()
        self.bert = BertModel(c)
        self.dropout = Dropout(c.hidden_dropout_prob)
        self.classifier = Linear(c.hidden_size, c.num_labels,
                                 weight_attr=Normal(std=c.initializer_range))

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is not None:
            loss = F.cross_entropy(logits, labels)
            return loss, logits
        return logits


class BertForMaskedLM(Layer):
    def __init__(self, config: Optional[BertConfig] = None):
        super().__init__()
        c = config or BertConfig()
        self.bert = BertModel(c)
        self.transform = Linear(c.hidden_size, c.hidden_size,
                                weight_attr=Normal(std=c.initializer_range))
        self.layer_norm = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.decoder = Linear(c.hidden_size, c.vocab_size,
                              weight_attr=Normal(std=c.initializer_range))
        self.config = c

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None):
        encoded, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.layer_norm(F.gelu(self.transform(encoded)))
        logits = self.decoder(h)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]), ignore_index=-100)
            return loss, logits
        return logits
