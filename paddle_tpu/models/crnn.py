"""CRNN: the OCR recognition model shape (BASELINE config 3, PP-OCR rec).

Capability parity: the reference ecosystem's CRNN/PP-OCRv4 recognition head
(conv backbone → collapse height → bidirectional LSTM encoder → per-timestep
classifier → CTC).  TPU-native: the conv stack and the per-timestep linear
are MXU matmuls; the BiLSTM is the lax.scan RNN from nn/layer/rnn.py; CTC is
the scan-based loss in nn/functional/ctc.py — the whole train step compiles
into one XLA program under jit.TrainStep.
"""
from __future__ import annotations

from ..nn import (
    BatchNorm2D, Conv2D, Layer, Linear, LSTM, MaxPool2D, ReLU, Sequential,
)


class CRNN(Layer):
    """Input [N, C, H, W] (H divisible by 4 after two 2x pools collapses to
    the sequence axis W//4); output logits [T=W//4, N, num_classes]
    (time-major, ready for ctc_loss)."""

    def __init__(self, num_classes, in_channels=1, img_height=32,
                 hidden_size=96, channels=(32, 64, 128)):
        super().__init__()
        if img_height % 4 != 0:
            raise ValueError("img_height must be divisible by 4 "
                             "(two 2x poolings collapse it)")
        c1, c2, c3 = channels
        self.backbone = Sequential(
            Conv2D(in_channels, c1, 3, padding=1), BatchNorm2D(c1), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(c1, c2, 3, padding=1), BatchNorm2D(c2), ReLU(),
            MaxPool2D(2, 2),
            Conv2D(c2, c3, 3, padding=1), BatchNorm2D(c3), ReLU(),
        )
        self.rnn = LSTM(c3 * (img_height // 4), hidden_size, num_layers=2,
                        direction="bidirect", time_major=False)
        self.head = Linear(2 * hidden_size, num_classes)
        self.num_classes = num_classes

    def forward(self, x):
        feat = self.backbone(x)                       # [N, C3, H/4, W/4]
        n, c, h, w = feat.shape
        seq = feat.transpose([0, 3, 1, 2]).reshape([n, w, c * h])
        enc, _ = self.rnn(seq)                        # [N, T, 2*hidden]
        logits = self.head(enc)                       # [N, T, classes]
        return logits.transpose([1, 0, 2])            # [T, N, classes]


def crnn_tiny(num_classes, in_channels=1, img_height=16):
    """Small config for tests/benchmarks."""
    return CRNN(num_classes, in_channels, img_height, hidden_size=48,
                channels=(16, 32, 64))
