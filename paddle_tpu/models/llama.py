"""LLaMA-family decoder LM — the flagship model (BASELINE config 5).

Capability parity: the reference trains LLaMA-2 via PaddleNLP on Fleet hybrid
parallel; the architecture blocks it relies on (fused rope, rms_norm, flash
attention, fused SwiGLU — paddle/phi/kernels/fusion/) appear here as
XLA-fused ops + the Pallas flash-attention kernel.

TPU-native: bf16 params/compute with fp32 master weights in the optimizer;
GQA; rotary embeddings precomputed in fp32; causal flash attention (Pallas on
TPU).  ``shard_llama`` stamps the canonical TP/FSDP placements (SURVEY §7
mesh axes) so the same model runs 1-chip or hybrid-parallel unchanged.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, wrap_array
from ..framework.dispatch import call_op, def_op
from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.common import Linear, Embedding, Dropout
from ..nn.layer.norm import RMSNorm
from ..nn import functional as F
from ..nn.initializer import Normal
from .. import tensor as T


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: Optional[int] = None
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: str = "float32"
    # Activation recomputation per decoder layer (reference:
    # use_recompute in PaddleNLP model configs + fleet.recompute) —
    # jax.checkpoint under the whole-step compile, trading one extra
    # forward for O(1-layer) activation residency.  The lever that fits
    # batch 8/16 pretrain into a single chip's HBM.
    use_recompute: bool = False

    def __post_init__(self):
        if self.num_key_value_heads is None:
            self.num_key_value_heads = self.num_attention_heads


def llama_7b():
    return LlamaConfig()


def llama_small(vocab=32000):
    """~110M-param config for single-chip benchmarking."""
    return LlamaConfig(vocab_size=vocab, hidden_size=768,
                       intermediate_size=2048, num_hidden_layers=12,
                       num_attention_heads=12, num_key_value_heads=12,
                       max_position_embeddings=2048)


def _rope_tables(head_dim, max_pos, theta):
    inv = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64)
                           / head_dim))
    t = np.arange(max_pos, dtype=np.float64)
    freqs = np.outer(t, inv)
    return (jnp.asarray(np.cos(freqs), jnp.float32),
            jnp.asarray(np.sin(freqs), jnp.float32))


@def_op("fused_rope")
def apply_rope(q, k, cos, sin, position_offset=0):
    """Rotary embedding on (b, s, h, d) — the reference's fused_rope kernel
    (paddle/phi/kernels/fusion/gpu/fused_rope_*).  XLA fuses the chain by
    default; per shape, ops/autotune may select the single-pass Pallas
    kernel (ops/pallas/fused_norm_rope.py, custom_vjp so training
    differentiates through it) on TPU."""
    from ..ops import autotune as _autotune
    from ..ops.pallas.fused_norm_rope import (fused_rope_fused,
                                              fused_rope_xla)

    s = q.shape[1]
    if getattr(position_offset, "ndim", 0) == 1:
        # per-row positions (continuous batching: each sequence in the
        # decode batch sits at its own length) — gather each row's angle
        # window instead of one shared dynamic slice
        if isinstance(position_offset, np.ndarray):
            # host-side bound check — free; device-resident/traced pos
            # is NOT pulled back (that would force a sync per layer per
            # decode step); callers feeding device arrays must bound
            # positions themselves (the batching engine does at submit)
            hi = int(position_offset.max()) + s
            if hi > cos.shape[0]:
                raise ValueError(
                    f"rope position {hi} exceeds the table ({cos.shape[0]}"
                    " = max_position_embeddings); the gather would "
                    "silently clamp and reuse the last angles")
        pos = jnp.asarray(position_offset, jnp.int32)      # (b,)
        idx = pos[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
        c = cos[idx][:, :, None, :]                        # (b, s, 1, half)
        si = sin[idx][:, :, None, :]

        def rot(x):
            half = x.shape[-1] // 2
            x1 = x[..., :half].astype(jnp.float32)
            x2 = x[..., half:].astype(jnp.float32)
            return jnp.concatenate(
                [x1 * c - x2 * si, x2 * c + x1 * si],
                axis=-1).astype(x.dtype)

        return rot(q), rot(k)
    if not isinstance(position_offset, jax.core.Tracer) \
            and int(position_offset) + s > cos.shape[0]:
        raise ValueError(
            f"rope position {int(position_offset) + s} exceeds the table "
            f"({cos.shape[0]} = max_position_embeddings); dynamic_slice "
            "would silently clamp and reuse the last angles")
    c = jax.lax.dynamic_slice_in_dim(cos, position_offset, s)
    si = jax.lax.dynamic_slice_in_dim(sin, position_offset, s)

    key = f"fused_rope:{tuple(q.shape)}:{tuple(k.shape)}:{q.dtype}"
    impl = _autotune.select(
        key, q,
        {"xla": lambda: fused_rope_xla(q, k, c, si),
         "pallas": lambda: fused_rope_fused(q, k, c, si)},
        default="xla")
    if impl == "pallas":
        return fused_rope_fused(q, k, c, si)
    return fused_rope_xla(q, k, c, si)


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv_heads = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        init = Normal(std=0.02)
        self.q_proj = Linear(c.hidden_size, self.num_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.k_proj = Linear(c.hidden_size, self.num_kv_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.v_proj = Linear(c.hidden_size, self.num_kv_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, c.hidden_size,
                             weight_attr=init, bias_attr=False)

    def forward(self, x, cos, sin, position_offset=0, kv_cache=None,
                paged_ctx=None):
        b, s = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        q, k = apply_rope(q, k, cos, sin, position_offset)
        if paged_ctx is not None:
            out = paged_ctx.attend(q, k, v)
            return self.o_proj(
                out.reshape([b, s, self.num_heads * self.head_dim]))
        new_cache = None
        if kv_cache is not None:
            pk, pv = kv_cache
            k = T.concat([pk, k], axis=1)
            v = T.concat([pv, v], axis=1)
            new_cache = (k, v)
        out, _ = F.flash_attention(q, k, v, causal=True)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        out = self.o_proj(out)
        if new_cache is not None:
            return out, new_cache
        return out


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        init = Normal(std=0.02)
        self.gate_proj = Linear(c.hidden_size, c.intermediate_size,
                                weight_attr=init, bias_attr=False)
        self.up_proj = Linear(c.hidden_size, c.intermediate_size,
                              weight_attr=init, bias_attr=False)
        self.down_proj = Linear(c.intermediate_size, c.hidden_size,
                                weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.mlp = LlamaMLP(config)

    def forward(self, x, cos, sin, position_offset=0, kv_cache=None,
                paged_ctx=None):
        attn_in = self.input_layernorm(x)
        if paged_ctx is not None:
            attn_out = self.self_attn(attn_in, cos, sin, position_offset,
                                      paged_ctx=paged_ctx)
            new_cache = None
        elif kv_cache is not None:
            attn_out, new_cache = self.self_attn(attn_in, cos, sin,
                                                 position_offset, kv_cache)
        else:
            attn_out = self.self_attn(attn_in, cos, sin, position_offset)
            new_cache = None
        x = x + attn_out
        x = x + self.mlp(self.post_attention_layernorm(x))
        if new_cache is not None:
            return x, new_cache
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=Normal(std=0.02))
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        cos, sin = _rope_tables(
            config.hidden_size // config.num_attention_heads,
            config.max_position_embeddings, config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, position_offset=0, kv_caches=None,
                paged_ctx=None):
        x = self.embed_tokens(input_ids)
        new_caches = [] if kv_caches is not None else None
        for i, layer in enumerate(self.layers):
            if paged_ctx is not None:
                paged_ctx.layer_idx = i
                x = layer(x, self.rope_cos, self.rope_sin, position_offset,
                          paged_ctx=paged_ctx)
            elif kv_caches is not None:
                x, cache = layer(x, self.rope_cos, self.rope_sin,
                                 position_offset, kv_caches[i])
                new_caches.append(cache)
            elif self.config.use_recompute:
                # fleet.recompute = jax.checkpoint: the layer's
                # activations are rematerialized inside the compiled
                # backward instead of living in HBM across the step
                from ..distributed.fleet.recompute import recompute
                # position_offset rides as a kwarg so it stays a static
                # Python int under the checkpoint trace (as in the
                # non-recompute call) instead of being wrapped to a
                # traced scalar
                x = recompute(layer, x, self.rope_cos, self.rope_sin,
                              position_offset=position_offset)
            else:
                x = layer(x, self.rope_cos, self.rope_sin, position_offset)
        x = self.norm(x)
        if new_caches is not None:
            return x, new_caches
        return x


def empty_kv_caches(model, batch: int):
    """One empty (k, v) cache pair per layer for the eager decode path —
    THE cache-layout contract shared by ``generate``, speculative
    decoding, and tests (shape [batch, 0, kv_heads, head_dim] in the
    embedding dtype; works for any causal LM with ``.config`` and
    ``.model.embed_tokens``)."""
    cfg = model.config
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    dtype = model.model.embed_tokens.weight._data.dtype
    empty = wrap_array(jnp.zeros(
        (batch, 0, cfg.num_key_value_heads, head_dim), dtype))
    return [(empty, empty) for _ in range(cfg.num_hidden_layers)]


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.model = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=Normal(std=0.02),
                                  bias_attr=False)

    def forward(self, input_ids, labels=None):
        hidden = self.model(input_ids)
        logits = self._logits_of(hidden)
        if labels is not None:
            loss = F.cross_entropy(
                logits.reshape([-1, self.config.vocab_size]),
                labels.reshape([-1]), ignore_index=-100)
            return loss, logits
        return logits

    def _logits_of(self, hidden):
        if self.lm_head is not None:
            return self.lm_head(hidden)
        return call_op("tied_lm_head", lambda h, w: jnp.matmul(h, w.T),
                       (hidden, self.model.embed_tokens.weight), {})

    def generate(self, input_ids, max_new_tokens: int = 32,
                 temperature: float = 1.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, do_sample: bool = False,
                 eos_token_id: Optional[int] = None, seed: int = 0):
        """Autoregressive decoding with a KV cache (reference capability:
        PaddleNLP generate / paddle.incubate block_multihead_attention
        serving path).  Greedy by default; temperature/top-k/top-p
        sampling with ``do_sample=True``.  Runs eagerly — each step
        reuses the cached K/V so cost is O(new_tokens * seq)."""
        import numpy as np
        from ..framework.tape import no_grad

        with no_grad():
            ids = input_ids
            # prefill: run the prompt once, building the cache
            caches = empty_kv_caches(self, int(ids.shape[0]))
            hidden, caches = self.model(ids, 0, caches)
            logits = self._logits_of(hidden[:, -1:])
            out_tokens = [ids]
            rng = np.random.default_rng(seed)
            finished = np.zeros(int(ids.shape[0]), bool)
            pos = int(ids.shape[1])
            for _ in range(max_new_tokens):
                step_logits = np.asarray(
                    logits._data[:, -1].astype(jnp.float32))
                if do_sample:
                    if temperature and temperature != 1.0:
                        step_logits = step_logits / max(temperature, 1e-6)
                    if top_k is not None:
                        kth = np.partition(
                            step_logits, -top_k, axis=-1)[:, -top_k][:, None]
                        step_logits = np.where(step_logits < kth,
                                               -np.inf, step_logits)
                    if top_p is not None:
                        sort_idx = np.argsort(-step_logits, axis=-1)
                        sorted_l = np.take_along_axis(step_logits, sort_idx,
                                                      axis=-1)
                        probs = np.exp(sorted_l - sorted_l.max(-1,
                                                               keepdims=True))
                        probs /= probs.sum(-1, keepdims=True)
                        cum = probs.cumsum(-1)
                        cut = cum - probs > top_p
                        sorted_l[cut] = -np.inf
                        restored = np.full_like(step_logits, -np.inf)
                        np.put_along_axis(restored, sort_idx, sorted_l,
                                          axis=-1)
                        step_logits = restored
                    p = np.exp(step_logits
                               - step_logits.max(-1, keepdims=True))
                    p /= p.sum(-1, keepdims=True)
                    nxt = np.array([rng.choice(p.shape[-1], p=p[b])
                                    for b in range(p.shape[0])])
                else:
                    nxt = step_logits.argmax(-1)
                if eos_token_id is not None:
                    nxt = np.where(finished, eos_token_id, nxt)
                    finished |= nxt == eos_token_id
                nxt_t = wrap_array(jnp.asarray(nxt[:, None], jnp.int32))
                out_tokens.append(nxt_t)
                if eos_token_id is not None and finished.all():
                    break
                hidden, caches = self.model(nxt_t, pos, caches)
                logits = self._logits_of(hidden)
                pos += 1
        from .. import tensor as T
        return T.concat(out_tokens, axis=1)


# ----------------------------------------------------------- parallel plan
def shard_llama(model: LlamaForCausalLM, mesh, dp_axis="dp", tp_axis="mp",
                fsdp_axis: Optional[str] = None):
    """Canonical TP(+FSDP) placements for the LLaMA stack
    (reference capability: PaddleNLP LLaMA + Fleet mp/sharding; SURVEY §7
    mesh-axis mapping; sharding recipe per the public scaling-book pattern).

    Column-parallel: q/k/v/gate/up (out-dim on tp).  Row-parallel:
    o_proj/down (in-dim on tp).  Embedding/lm_head: vocab on tp.  FSDP axis
    (optional) shards the other weight dim.
    """
    from ..distributed.auto_parallel.placement import Shard, Replicate
    from ..distributed.auto_parallel.api import shard_tensor

    names = dict(mesh_axis=(mesh.dim_names))

    def place(param, tp_dim, fsdp_dim=None):
        placements = [Replicate()] * mesh.ndim
        if tp_axis in mesh.dim_names and tp_dim is not None:
            if param.shape[tp_dim] % mesh.get_dim_size(tp_axis) == 0:
                placements[mesh.dim_names.index(tp_axis)] = Shard(tp_dim)
        if fsdp_axis and fsdp_axis in mesh.dim_names and fsdp_dim is not None:
            if param.shape[fsdp_dim] % mesh.get_dim_size(fsdp_axis) == 0:
                placements[mesh.dim_names.index(fsdp_axis)] = Shard(fsdp_dim)
        shard_tensor(param, mesh, placements)

    for layer in model.model.layers:
        attn, mlp = layer.self_attn, layer.mlp
        place(attn.q_proj.weight, 1, 0)
        place(attn.k_proj.weight, 1, 0)
        place(attn.v_proj.weight, 1, 0)
        place(attn.o_proj.weight, 0, 1)
        place(mlp.gate_proj.weight, 1, 0)
        place(mlp.up_proj.weight, 1, 0)
        place(mlp.down_proj.weight, 0, 1)
        place(layer.input_layernorm.weight, None, 0)
        place(layer.post_attention_layernorm.weight, None, 0)
    place(model.model.embed_tokens.weight, 1, 0)
    if model.lm_head is not None:
        place(model.lm_head.weight, 1, 0)
    place(model.model.norm.weight, None, 0)
    return model
