"""Mixtral-style sparse-MoE LLaMA decoder — the MoE model family the
reference trains through its EP stack (reference capability:
python/paddle/incubate/distributed/models/moe/moe_layer.py MoELayer +
the decoder architecture of models/llama.py here).

TPU-native end to end: attention is the shared LlamaAttention (Pallas
flash path on TPU), each decoder's FFN is a MoELayer over an ExpertFFN
with stacked [E, ...] weights (batched on the MXU, shardable over an
'ep' mesh axis via shard_moe_layer), routing is the ragged O(T)
scatter/gather dispatch, and the gate's load-balancing auxiliary loss is
returned alongside the logits so the whole thing compiles into one
donated-buffer TrainStep program.
"""
from __future__ import annotations

from dataclasses import dataclass

from ..nn.layer.layers import Layer, LayerList
from ..nn.layer.common import Linear, Embedding
from ..nn.layer.norm import RMSNorm
from ..nn import functional as F
from ..nn.initializer import Normal
from ..framework.tensor import Tensor
from ..incubate.distributed.models.moe import ExpertFFN, MoELayer
from .llama import LlamaAttention, LlamaConfig, _rope_tables


@dataclass
class LlamaMoeConfig(LlamaConfig):
    """LlamaConfig + sparse-MoE routing knobs (Mixtral shape family).

    ``moe_top_k=None`` (default) picks the gate's canonical k: 2 for
    gshard/naive, 1 for switch (switch routing is top-1 by definition;
    an explicit mismatched k is corrected with a warning by MoELayer).
    """
    num_experts: int = 8
    moe_top_k: int = None              # None -> gate-appropriate default
    gate_type: str = "gshard"          # gshard | switch | naive
    aux_loss_weight: float = 0.01

    def __post_init__(self):
        super().__post_init__()
        if self.moe_top_k is None:
            self.moe_top_k = 1 if self.gate_type == "switch" else 2


class LlamaMoeDecoderLayer(Layer):
    """Attention + sparse-MoE FFN block.

    Recompute note: the whole layer must NOT be wrapped in one
    jax.checkpoint — the gate records its load-balancing loss as a side
    channel read after the forward, and trapping that inside a remat
    trace would detach it from the grad path.  use_recompute therefore
    remats the attention block and the expert FFNs separately
    (MoELayer's own recompute_interval), keeping the gate outside.
    """

    def __init__(self, config: LlamaMoeConfig):
        super().__init__()
        self.use_recompute = config.use_recompute
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       epsilon=config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                epsilon=config.rms_norm_eps)
        self.moe = MoELayer(
            config.hidden_size,
            ExpertFFN(config.num_experts, config.hidden_size,
                      config.intermediate_size, activation="swiglu"),
            gate={"type": config.gate_type, "top_k": config.moe_top_k},
            recompute_interval=1 if config.use_recompute else 0)

    def forward(self, x, cos, sin, position_offset=0, kv_cache=None):
        attn_in = self.input_layernorm(x)
        if kv_cache is not None:
            attn_out, new_cache = self.self_attn(attn_in, cos, sin,
                                                 position_offset, kv_cache)
        else:
            new_cache = None
            if self.use_recompute and self.training:
                from ..distributed.fleet.recompute import recompute
                attn_out = recompute(self.self_attn, attn_in, cos, sin,
                                     position_offset=position_offset)
            else:
                attn_out = self.self_attn(attn_in, cos, sin,
                                          position_offset)
        x = x + attn_out
        x = x + self.moe(self.post_attention_layernorm(x))
        if new_cache is not None:
            return x, new_cache
        return x


class LlamaMoeModel(Layer):
    def __init__(self, config: LlamaMoeConfig):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(config.vocab_size, config.hidden_size,
                                      weight_attr=Normal(std=0.02))
        self.layers = LayerList([LlamaMoeDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, epsilon=config.rms_norm_eps)
        cos, sin = _rope_tables(
            config.hidden_size // config.num_attention_heads,
            config.max_position_embeddings, config.rope_theta)
        self.register_buffer("rope_cos", Tensor(cos), persistable=False)
        self.register_buffer("rope_sin", Tensor(sin), persistable=False)

    def forward(self, input_ids, position_offset=0, kv_caches=None):
        x = self.embed_tokens(input_ids)
        new_caches = [] if kv_caches is not None else None
        for i, layer in enumerate(self.layers):
            if kv_caches is not None:
                x, cache = layer(x, self.rope_cos, self.rope_sin,
                                 position_offset, kv_caches[i])
                new_caches.append(cache)
            else:
                # recompute happens INSIDE the layer (attention + expert
                # FFN blocks) so the gate's aux-loss side channel stays
                # on the grad path — see LlamaMoeDecoderLayer
                x = layer(x, self.rope_cos, self.rope_sin, position_offset)
        x = self.norm(x)
        if new_caches is not None:
            return x, new_caches
        return x

    def aux_loss(self):
        """Sum of per-layer gate load-balancing losses (cleared on read,
        like the reference's gate.get_loss(clear=True) contract)."""
        total = None
        for layer in self.layers:
            la = layer.moe.gate.get_loss(clear=True)
            if la is None:
                continue
            total = la if total is None else total + la
        return total


class LlamaMoeForCausalLM(Layer):
    """Causal LM over the MoE decoder.

    ``forward`` returns ``(logits, aux)`` — the gate's weighted
    load-balancing loss rides next to the logits so a TrainStep
    ``loss_fn(outputs, labels)`` can add it inside the one compiled
    program: ``loss = ce(logits, labels) + aux``.
    """

    def __init__(self, config: LlamaMoeConfig):
        super().__init__()
        self.config = config
        self.model = LlamaMoeModel(config)
        self.lm_head = Linear(config.hidden_size, config.vocab_size,
                              weight_attr=Normal(std=0.02), bias_attr=False)

    def forward(self, input_ids):
        hidden = self.model(input_ids)
        logits = self.lm_head(hidden)
        aux = self.model.aux_loss()
        if aux is None:
            from .. import tensor as T
            aux = T.zeros([], dtype="float32")
        return logits, aux * self.config.aux_loss_weight

    def _logits_of(self, hidden):
        return self.lm_head(hidden)

    # the cache-path decode loop is model-agnostic (it drives
    # self.model(ids, offset, caches) + self._logits_of) — reuse the
    # dense LLaMA implementation verbatim
    from .llama import LlamaForCausalLM as _Dense
    generate = _Dense.generate
    del _Dense


def shard_llama_moe(model: LlamaMoeForCausalLM, mesh, dp_axis="dp",
                    tp_axis=None, ep_axis="ep"):
    """Canonical hybrid placements for the MoE decoder: expert weights
    Shard(0) over ``ep_axis`` (GSPMD inserts the token all_to_all the
    reference issues by hand — moe_layer.py:119,167 global_scatter/
    global_gather), gates replicated, and optionally Megatron TP on the
    attention projections + lm_head over ``tp_axis``.  Data rides
    ``dp_axis`` via the input sharding (caller's batch placement)."""
    from ..distributed.auto_parallel.placement import Shard, Replicate
    from ..distributed.auto_parallel.api import shard_tensor
    from ..incubate.distributed.models.moe import shard_moe_layer

    def place(param, tp_dim):
        placements = [Replicate()] * mesh.ndim
        if tp_axis and tp_axis in mesh.dim_names and tp_dim is not None:
            if param.shape[tp_dim] % mesh.get_dim_size(tp_axis) == 0:
                placements[mesh.dim_names.index(tp_axis)] = Shard(tp_dim)
        shard_tensor(param, mesh, placements)

    place(model.model.embed_tokens.weight, None)
    place(model.model.norm.weight, None)
    place(model.lm_head.weight, 1)
    for layer in model.model.layers:
        attn = layer.self_attn
        place(attn.q_proj.weight, 1)        # column-parallel
        place(attn.k_proj.weight, 1)
        place(attn.v_proj.weight, 1)
        place(attn.o_proj.weight, 0)        # row-parallel
        place(layer.input_layernorm.weight, None)
        place(layer.post_attention_layernorm.weight, None)
        shard_moe_layer(layer.moe, mesh, axis=ep_axis)
    return model
