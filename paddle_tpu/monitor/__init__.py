"""paddle_tpu.monitor — unified runtime telemetry.

A process-wide metrics registry (Counter / Gauge / Histogram with fixed
log-scale buckets; thread-safe, stdlib-only) plus span tracing that
feeds the profiler's host recorder.  Instrumented subsystems:

  * ``distributed.collective`` — per-kind call count, latency and
    payload-bytes histograms on every eager collective;
  * ``inference.server`` — request count/latency per route, a
    ``GET /metrics`` Prometheus endpoint on both servers;
  * ``inference.continuous`` — queue depth, batch-slot occupancy,
    decode-step latency, generated-token and TTFT telemetry;
  * ``hapi.callbacks.MonitorCallback`` — step time, samples/sec, loss;
  * ``distributed.watchdog`` / ``fault_tolerance`` — heartbeat age,
    in-flight/timeout tasks, preemption/restart/checkpoint counters.

Usage::

    from paddle_tpu import monitor
    h = monitor.histogram("my_latency_seconds", "...", ("stage",))
    with monitor.span("stage/io", histogram=h, stage="io"):
        ...
    print(monitor.prometheus_text())     # or monitor.snapshot()
    monitor.dump_on_exit()               # archive at interpreter exit
"""
from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricRegistry, get_registry,
    counter, gauge, histogram, snapshot, prometheus_text,
    dump, dump_on_exit, DEFAULT_LATENCY_BUCKETS, BYTES_BUCKETS,
)
from .span import span  # noqa: F401
from .compile_hooks import install_compile_hooks  # noqa: F401
from .trace import (  # noqa: F401
    Tracer, get_tracer, start_capture, stop_capture, request_timeline,
    export_chrome_trace, validate_chrome_trace,
)

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "get_registry",
    "counter", "gauge", "histogram", "snapshot", "prometheus_text",
    "dump", "dump_on_exit", "span", "install_compile_hooks",
    "DEFAULT_LATENCY_BUCKETS", "BYTES_BUCKETS",
    "Tracer", "get_tracer", "start_capture", "stop_capture",
    "request_timeline", "export_chrome_trace", "validate_chrome_trace",
]
