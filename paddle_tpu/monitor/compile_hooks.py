"""Compile telemetry: ``jit_recompile_count`` / ``jit_compile_seconds``.

jax fires a monitoring event for every XLA backend compile the process
performs; ``install_compile_hooks()`` subscribes once and feeds two
registry metrics, so the program auditor's static recompile rules
(``paddle_tpu.analysis``) and the runtime agree on what actually
recompiled.  Every event is a program the jit cache could not serve —
the first compile of a signature counts too, which is exactly what a
serving warm-up wants to see go to zero in the measured window
(tools/serve_bench.py surfaces the deltas).

jax builds without ``jax.monitoring`` degrade to a no-op through
``framework.jax_compat.register_compile_listener`` (returns False; the
metrics then simply never move).  This module must stay lazily
importable: nothing here touches jax until ``install_compile_hooks()``
is called, preserving the registry's importable-before-jax contract.
"""
from __future__ import annotations

import threading

from .registry import counter, histogram

__all__ = ["install_compile_hooks"]

_COMPILE_EVENT_MARKER = "backend_compile"
_RECOMPILE_HELP = ("XLA backend compiles observed (every event is a "
                   "program the jit cache could not serve; first "
                   "compiles of a signature count too)")
_SECONDS_HELP = "wall seconds per XLA backend compile"

_lock = threading.Lock()
_installed = False


def _on_event_duration(event: str, duration: float, **kw) -> None:
    if _COMPILE_EVENT_MARKER not in event:
        return
    # re-fetch per event: a registry.reset() (tests) drops the metric
    # objects, and get-or-create is one dict hit under the registry lock
    counter("jit_recompile_count", _RECOMPILE_HELP).inc()
    histogram("jit_compile_seconds", _SECONDS_HELP).observe(duration)


def install_compile_hooks() -> bool:
    """Idempotently subscribe to jax's compile events.  Returns True
    when the listener is (already) installed, False on jax builds with
    no monitoring hook (telemetry degrades to zeros, nothing breaks)."""
    global _installed
    with _lock:
        if _installed:
            return True
        from ..framework.jax_compat import register_compile_listener
        if not register_compile_listener(_on_event_duration):
            return False
        # materialize the series now so a snapshot taken before the
        # first compile still carries explicit zeros
        counter("jit_recompile_count", _RECOMPILE_HELP)
        histogram("jit_compile_seconds", _SECONDS_HELP)
        _installed = True
        return True
