"""Process-wide metrics registry: Counter / Gauge / Histogram.

The measurement substrate the ROADMAP's perf goals are graded against
(reference: Paddle Serving's serving-side monitoring + the profiler's
summary statistics; T3/arxiv 2401.16677 uses exactly this kind of
per-collective latency tracking to find overlap opportunities).

Design constraints:
  * zero dependencies — stdlib only, importable before jax;
  * thread-safe — the inference server observes from handler threads
    while the continuous-batching scheduler observes from its own;
  * histograms use FIXED log-scale buckets so merging/diffing snapshots
    across runs never has to re-bucket.

Exposition is dual: ``snapshot()`` (JSON-able dict, for bench artifacts)
and ``prometheus_text()`` (text exposition format 0.0.4, for scraping
the servers' ``GET /metrics``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "MetricRegistry", "get_registry",
    "counter", "gauge", "histogram", "snapshot", "prometheus_text",
    "dump", "dump_on_exit", "DEFAULT_LATENCY_BUCKETS", "BYTES_BUCKETS",
]

# ~1us .. ~34s in powers of two: latency from a single dispatch to a
# wedged collective, 26 buckets
DEFAULT_LATENCY_BUCKETS: Tuple[float, ...] = tuple(
    2.0 ** e for e in range(-20, 6))
# 1B .. ~1GiB in powers of four: collective payload sizes
BYTES_BUCKETS: Tuple[float, ...] = tuple(4.0 ** e for e in range(16))


def _check_labels(label_names: Tuple[str, ...], labels: Dict[str, str]
                  ) -> Tuple[str, ...]:
    if set(labels) != set(label_names):
        raise ValueError(
            f"expected labels {list(label_names)}, got {list(labels)}")
    return tuple(str(labels[k]) for k in label_names)


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.label_names = tuple(label_names)
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, ...], object] = {}

    def _key(self, labels: Dict[str, str]) -> Tuple[str, ...]:
        return _check_labels(self.label_names, labels)

    def labeled_series(self) -> List[Tuple[Dict[str, str], object]]:
        with self._lock:
            items = list(self._series.items())
        return [(dict(zip(self.label_names, k)), v) for k, v in items]


class Counter(_Metric):
    """Monotone count; ``inc`` only (reference: prometheus counter)."""

    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a Gauge")
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class Gauge(_Metric):
    """Point-in-time value; set/inc/dec."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = float(value)

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0.0) + amount

    def dec(self, amount: float = 1, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return float(self._series.get(self._key(labels), 0.0))


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, nbuckets: int):
        self.counts = [0] * nbuckets     # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Fixed-bucket histogram; ``le`` buckets are upper-inclusive like
    the prometheus exposition they serialize to."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 label_names: Sequence[str] = (),
                 buckets: Optional[Sequence[float]] = None):
        super().__init__(name, help, label_names)
        bk = tuple(sorted(buckets or DEFAULT_LATENCY_BUCKETS))
        if not bk:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bk

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        value = float(value)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                s = self._series[key] = _HistSeries(len(self.buckets))
            # first bucket with bound >= value (bisect is overkill for
            # ~26 fixed buckets and this stays allocation-free)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    s.counts[i] += 1
                    break
            s.sum += value
            s.count += 1

    def time(self, **labels):
        """``with hist.time(...):`` — observe the block's wall seconds."""
        from .span import span
        return span(self.name, histogram=self, **labels)

    # -- introspection (tests / snapshot) ------------------------------
    def cumulative_counts(self, **labels) -> List[int]:
        """Cumulative per-``le``-bucket counts; last entry is +Inf."""
        with self._lock:
            s = self._series.get(self._key(labels))
            if s is None:
                return [0] * (len(self.buckets) + 1)
            out, acc = [], 0
            for c in s.counts:
                acc += c
                out.append(acc)
            out.append(s.count)          # +Inf == total observations
            return out

    def sum_count(self, **labels) -> Tuple[float, int]:
        with self._lock:
            s = self._series.get(self._key(labels))
            return (s.sum, s.count) if s is not None else (0.0, 0)


class MetricRegistry:
    """Name -> metric; get-or-create with type/label consistency checks."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help, label_names, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help, label_names, **kwargs)
                self._metrics[name] = m
                return m
        if not isinstance(m, cls) or m.label_names != tuple(label_names):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind} with "
                f"labels {list(m.label_names)}")
        buckets = kwargs.get("buckets")
        if buckets is not None and tuple(sorted(buckets)) != m.buckets:
            raise ValueError(
                f"histogram {name!r} already registered with buckets "
                f"{m.buckets}")
        return m

    def counter(self, name: str, help: str = "",
                label_names: Sequence[str] = ()) -> Counter:
        return self._get_or_create(Counter, name, help, label_names)

    def gauge(self, name: str, help: str = "",
              label_names: Sequence[str] = ()) -> Gauge:
        return self._get_or_create(Gauge, name, help, label_names)

    def histogram(self, name: str, help: str = "",
                  label_names: Sequence[str] = (),
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, label_names,
                                   buckets=buckets)

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def reset(self) -> None:
        """Drop every metric (tests)."""
        with self._lock:
            self._metrics.clear()

    # -- exposition ----------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able dump of every series."""
        with self._lock:
            metrics = list(self._metrics.values())
        out = {}
        for m in metrics:
            series = []
            for labels, _ in m.labeled_series():
                if isinstance(m, Histogram):
                    s, c = m.sum_count(**labels)
                    series.append({
                        "labels": labels, "sum": s, "count": c,
                        "buckets": dict(zip(
                            [_fmt(b) for b in m.buckets] + ["+Inf"],
                            m.cumulative_counts(**labels)))})
                else:
                    series.append({"labels": labels,
                                   "value": m.value(**labels)})
            out[m.name] = {"type": m.kind, "help": m.help,
                           "series": series}
        return out

    def prometheus_text(self) -> str:
        """Text exposition format 0.0.4."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_esc_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for labels, _ in m.labeled_series():
                if isinstance(m, Histogram):
                    cum = m.cumulative_counts(**labels)
                    for b, c in zip(list(m.buckets) + [None], cum):
                        le = "+Inf" if b is None else _fmt(b)
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_lbl(labels, le=le)} {c}")
                    s, c = m.sum_count(**labels)
                    lines.append(f"{m.name}_sum{_lbl(labels)} {_fmt(s)}")
                    lines.append(f"{m.name}_count{_lbl(labels)} {c}")
                else:
                    lines.append(
                        f"{m.name}{_lbl(labels)} {_fmt(m.value(**labels))}")
        return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"                  # a diverged gauge must still scrape
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def _esc_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _esc_label(s: str) -> str:
    return (s.replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _lbl(labels: Dict[str, str], **extra) -> str:
    items = {**labels, **extra}
    if not items:
        return ""
    body = ",".join(f'{k}="{_esc_label(str(v))}"'
                    for k, v in items.items())
    return "{" + body + "}"


_global_registry = MetricRegistry()


def get_registry() -> MetricRegistry:
    return _global_registry


def counter(name: str, help: str = "",
            label_names: Sequence[str] = ()) -> Counter:
    return _global_registry.counter(name, help, label_names)


def gauge(name: str, help: str = "",
          label_names: Sequence[str] = ()) -> Gauge:
    return _global_registry.gauge(name, help, label_names)


def histogram(name: str, help: str = "", label_names: Sequence[str] = (),
              buckets: Optional[Sequence[float]] = None) -> Histogram:
    return _global_registry.histogram(name, help, label_names, buckets)


def snapshot() -> dict:
    return _global_registry.snapshot()


def prometheus_text() -> str:
    return _global_registry.prometheus_text()


# ------------------------------------------------------------ exit dump
def _default_dump_path() -> str:
    # bench runs execute from the repo root where tools/ lives; fall
    # back to the cwd so installed trees still get their archive
    tools = os.path.join(os.getcwd(), "tools")
    base = tools if os.path.isdir(tools) else os.getcwd()
    return os.path.join(base, "monitor_snapshots.jsonl")


def dump(path: Optional[str] = None) -> str:
    """Append one JSON line with the current snapshot (the same
    append-only audit-trail style as tools/tpu_probe_log.jsonl)."""
    path = path or _default_dump_path()
    rec = {"ts": round(time.time(), 1),
           "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
           "pid": os.getpid(),
           "snapshot": snapshot()}
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    return path


_dump_registered = threading.Lock()
_dump_paths: List[str] = []


def dump_on_exit(path: Optional[str] = None) -> str:
    """Archive the final snapshot at interpreter exit (idempotent per
    path); returns the path that will be written."""
    import atexit
    path = path or _default_dump_path()
    with _dump_registered:
        if path not in _dump_paths:
            if not _dump_paths:
                atexit.register(_dump_all)
            _dump_paths.append(path)
    return path


def _dump_all() -> None:
    for p in list(_dump_paths):
        try:
            dump(p)
        except Exception:
            pass
