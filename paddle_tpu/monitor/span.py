"""Lightweight span tracing bridging the metrics registry and the
profiler's host recorder.

A ``span`` times a block once and fans the measurement out to both
consumers: a Histogram observation (always, metrics are unconditional)
and a profiler ``HostEvent`` (only while a Profiler has the recorder in
a RECORD state — the push is a no-op otherwise, matching RecordEvent's
contract in profiler/record.py).
"""
from __future__ import annotations

import functools
import time
from typing import Optional

from ..profiler.record import get_recorder
from .registry import Histogram

__all__ = ["span"]


class span:
    """``with span("collective/all_reduce", histogram=h, kind="all_reduce"):``

    Times the block; observes elapsed seconds into ``histogram`` (with
    the given labels) and records a host event named ``name`` for the
    profiler timeline.  Usable as a decorator.  ``elapsed`` holds the
    measured seconds after exit.
    """

    __slots__ = ("name", "histogram", "labels", "elapsed",
                 "_t0", "_start_ns")

    def __init__(self, name: str, histogram: Optional[Histogram] = None,
                 **labels):
        self.name = name
        self.histogram = histogram
        self.labels = labels
        self.elapsed: Optional[float] = None
        self._t0 = None
        self._start_ns = None

    def __enter__(self):
        rec = get_recorder()
        if rec.enabled:
            self._start_ns = rec.now_ns()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self.histogram is not None:
            self.histogram.observe(self.elapsed, **self.labels)
        if self._start_ns is not None:
            rec = get_recorder()
            rec.push(self.name, self._start_ns, rec.now_ns())
            self._start_ns = None
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(self.name, self.histogram, **self.labels):
                return fn(*args, **kwargs)
        return wrapper
