"""Lightweight span tracing bridging the metrics registry and the
profiler's host recorder.

A ``span`` times a block once and fans the measurement out to both
consumers: a Histogram observation (always, metrics are unconditional)
and a profiler ``HostEvent`` (only while a Profiler has the recorder in
a RECORD state — the push is a no-op otherwise, matching RecordEvent's
contract in profiler/record.py).
"""
from __future__ import annotations

import functools
import time
from typing import Optional

from ..profiler.record import get_recorder
from .registry import Histogram

__all__ = ["span"]


class span:
    """``with span("collective/all_reduce", histogram=h, kind="all_reduce"):``

    Times the block; observes elapsed seconds into ``histogram`` (with
    the given labels) and records a host event named ``name`` for the
    profiler timeline.  Usable as a decorator.  ``elapsed`` holds the
    measured seconds after exit.
    """

    __slots__ = ("name", "histogram", "labels", "elapsed",
                 "_t0", "_start_ns")

    def __init__(self, name: str, histogram: Optional[Histogram] = None,
                 **labels):
        self.name = name
        self.histogram = histogram
        self.labels = labels
        self.elapsed: Optional[float] = None
        self._t0 = None
        self._start_ns = None

    def __enter__(self):
        rec = get_recorder()
        if rec.enabled:
            self._start_ns = rec.now_ns()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.elapsed = time.perf_counter() - self._t0
        if self.histogram is not None:
            self.histogram.observe(self.elapsed, **self.labels)
        if self._start_ns is not None:
            rec = get_recorder()
            rec.push(self.name, self._start_ns, rec.now_ns())
            self._start_ns = None
        return False

    def __call__(self, fn):
        """Decorator form.  Each call times through a fresh inner span
        (the decorator instance's config — name/histogram/labels —
        is resolved ONCE, here) and the measurement is copied back to
        THIS instance's ``elapsed``, so tests can read the decorator
        they hold instead of losing the inner span (the old form
        silently dropped it).  Per-call inner spans keep re-entrant
        and concurrent calls from clobbering each other's timers."""
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            inner = span(self.name, self.histogram, **self.labels)
            try:
                with inner:
                    return fn(*args, **kwargs)
            finally:
                self.elapsed = inner.elapsed
        return wrapper
