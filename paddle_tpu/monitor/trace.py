"""Request-level tracing + engine step timeline (ISSUE 10 tentpole).

The metrics registry answers "how is serving doing on average"; this
module answers "where did THIS request's 40ms go" — queue wait, each
prefill chunk, every decode/verify step it rode, a preemption, a
survivor replay — and "what did the engine do each step" (batch
composition per class, chunk tokens spent, speculative economics,
dispatch wall time).  MLPerf-0.6's TPU scaling analysis and T3 (see
PAPERS.md) both start from exactly this per-step attribution; the
compute/collective overlap work on the ROADMAP will extend the same
step track with collective spans.

Design constraints:

  * **off by default, ~free when off** — every record call starts with
    a plain attribute read (``tracer.enabled``); outside a capture
    window the serving hot path pays one predictable branch per probe,
    nothing else (the serve_bench decode-step p50 gate rides on this);
  * **bounded** — per-request timelines cap their event count, the
    request table caps its size (oldest evicted), and the engine-step
    ring is a fixed ``deque``; overflow increments
    ``trace_dropped_events_total`` instead of growing;
  * **one clock** — timestamps are ``time.perf_counter_ns()``, the
    same clock the profiler's Python recorder stamps ``HostEvent``s
    with, so ``export_chrome_trace`` merges span/host events onto the
    request/step tracks without skew arithmetic;
  * **stdlib only** — importable before jax, like the rest of
    ``paddle_tpu.monitor``.

Usage::

    from paddle_tpu import monitor
    monitor.start_capture()            # opens the window
    ... serve traffic ...
    monitor.stop_capture()
    payload = monitor.export_chrome_trace("trace.json")  # Perfetto/chrome
    monitor.request_timeline("req-abc")  # one request's event list

The serving surface mirrors this over HTTP: ``POST /debug/trace/start``
/ ``POST /debug/trace/stop``, ``GET /debug/trace`` and
``GET /debug/requests/<id>`` on the GenerationServer
(``tools/trace_capture.py`` is the CLI driver).
"""
from __future__ import annotations

import json
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from .registry import counter, gauge

__all__ = [
    "Tracer", "get_tracer", "start_capture", "stop_capture",
    "request_timeline", "export_chrome_trace", "validate_chrome_trace",
]

# capture telemetry — materialized at import so the series exist in
# monitor.snapshot() / the smoke gates even before the first window
_captures_total = counter(
    "trace_captures_total", "capture windows opened via start_capture()")
_events_total = counter(
    "trace_events_total", "request/step trace events recorded inside "
    "capture windows")
_dropped_total = counter(
    "trace_dropped_events_total", "trace events dropped by the bounded "
    "buffers (per-request event cap, request-table cap)")
_active_g = gauge(
    "trace_capture_active", "1 while a trace capture window is open")
_captures_total.inc(0)
_events_total.inc(0)
_dropped_total.inc(0)
_active_g.set(0)

#: event kinds that tie a request's lifecycle to an engine-step track
#: entry — exported as chrome FLOW events (request track -> step track)
_FLOW_KINDS = frozenset({"prefill_chunk", "decode_step", "verify_step"})


class _Timeline:
    """One request's bounded event list: (ts_ns, kind, detail)."""

    __slots__ = ("request_id", "events", "dropped")

    def __init__(self, request_id: str):
        self.request_id = request_id
        self.events: List[Tuple[int, str, Optional[dict]]] = []
        self.dropped = 0

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "dropped_events": self.dropped,
            "events": [
                {"ts_ns": ts, "kind": kind, **({} if not d else d)}
                for ts, kind, d in self.events],
        }


class Tracer:
    """The process-wide trace buffer: per-request timelines + the
    engine-step ring.  All mutation is behind one small lock; the
    disabled fast path is a single attribute read."""

    def __init__(self):
        self.enabled = False              # the hot-path gate (plain read)
        self._lock = threading.Lock()
        self._requests: "OrderedDict[str, _Timeline]" = OrderedDict()
        self._steps: deque = deque(maxlen=2048)
        self._max_requests = 256
        self._max_events_per_request = 512
        self._host_events: List = []
        self._rec_enabled_here = False
        self._started_ns = 0
        self._stopped_ns = 0

    # ------------------------------------------------------------- window
    @staticmethod
    def now_ns() -> int:
        return time.perf_counter_ns()

    def start_capture(self, max_requests: int = 256,
                      max_events_per_request: int = 512,
                      max_steps: int = 2048,
                      host_events: bool = True) -> None:
        """Open a capture window (drops any previous buffer).  With
        ``host_events`` the profiler's host recorder is enabled for the
        window too — ``monitor.span`` probes (engine/prefill,
        engine/decode_step, http routes, collectives) then land on the
        exported timeline next to the request/step tracks.  If a
        Profiler already owns the recorder it is left alone (its
        events are not stolen)."""
        from ..profiler.record import get_recorder
        with self._lock:
            if self.enabled:
                # Re-entrant start (retried HTTP request, overlapping
                # operators): keep the open window rather than clobber
                # _rec_enabled_here — losing that flag would leave the
                # host recorder enabled (and unbounded) forever.
                return
            self._requests = OrderedDict()
            self._steps = deque(maxlen=int(max_steps))
            self._max_requests = int(max_requests)
            self._max_events_per_request = int(max_events_per_request)
            self._host_events = []
            self._started_ns = self.now_ns()
            self._stopped_ns = 0
            rec = get_recorder()
            self._rec_enabled_here = host_events and not rec.enabled
            if self._rec_enabled_here:
                rec.collect()            # drop stale pre-window events
                rec.enable(True)
            self.enabled = True
        _captures_total.inc()
        _active_g.set(1)

    def stop_capture(self) -> None:
        """Close the window.  The buffer stays readable (export /
        timeline queries) until the next ``start_capture``."""
        from ..profiler.record import get_recorder
        with self._lock:
            if not self.enabled:
                return
            self.enabled = False
            self._stopped_ns = self.now_ns()
            if self._rec_enabled_here:
                rec = get_recorder()
                self._host_events = rec.collect()
                rec.enable(False)
                self._rec_enabled_here = False
        _active_g.set(0)

    # ------------------------------------------------------------- record
    def request_event(self, request_id: Optional[str], kind: str,
                      **detail) -> None:
        """Append one event to a request's timeline (no-op outside a
        capture window or for id-less requests)."""
        if not self.enabled or request_id is None:
            return
        ts = self.now_ns()
        with self._lock:
            tl = self._requests.get(request_id)
            if tl is None:
                if len(self._requests) >= self._max_requests:
                    self._requests.popitem(last=False)
                    _dropped_total.inc()
                tl = self._requests[request_id] = _Timeline(request_id)
            if len(tl.events) >= self._max_events_per_request:
                tl.dropped += 1
                _dropped_total.inc()
                return
            tl.events.append((ts, kind, detail or None))
        _events_total.inc()

    def step_record(self, kind: str, index: int, start_ns: int,
                    end_ns: int, **data) -> None:
        """Append one engine-step record to the bounded ring."""
        if not self.enabled:
            return
        with self._lock:
            self._steps.append((kind, int(index), int(start_ns),
                                int(end_ns), data or None))
        _events_total.inc()

    # -------------------------------------------------------------- query
    def request_timeline(self, request_id: str) -> Optional[dict]:
        with self._lock:
            tl = self._requests.get(request_id)
            return None if tl is None else tl.to_dict()

    def request_ids(self) -> List[str]:
        with self._lock:
            return list(self._requests)

    def step_records(self) -> List[dict]:
        with self._lock:
            steps = list(self._steps)
        return [{"kind": k, "index": i, "start_ns": s, "end_ns": e,
                 **({} if not d else d)} for k, i, s, e, d in steps]

    # ------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict:
        """Chrome-trace/Perfetto JSON: the engine-step track (pid 1),
        one track per request (pid 2, flow-linked to the step track at
        every chunk/decode/verify participation), and the window's
        profiler ``HostEvent`` spans (pid 3) — all on one clock."""
        with self._lock:
            steps = list(self._steps)
            timelines = list(self._requests.values())
            host = list(self._host_events)
        ev: List[dict] = []

        def meta(pid, name):
            ev.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "ts": 0.0, "args": {"name": name}})

        meta(1, "engine steps")
        meta(2, "requests")
        meta(3, "host spans")
        for kind, idx, s_ns, e_ns, data in steps:
            ev.append({
                "name": kind, "ph": "X", "cat": "engine", "pid": 1,
                "tid": 0, "ts": s_ns / 1e3,
                "dur": max(0, e_ns - s_ns) / 1e3,
                "args": {"step": idx, **(data or {})}})
        flow_id = 1
        for tid, tl in enumerate(timelines, start=1):
            if not tl.events:
                continue
            first_ts = tl.events[0][0]
            last_ts = tl.events[-1][0]
            name = f"request {tl.request_id}"
            ev.append({"name": name, "ph": "B", "cat": "request",
                       "pid": 2, "tid": tid, "ts": first_ts / 1e3,
                       "args": {"request_id": tl.request_id}})
            for ts, kind, detail in tl.events:
                ev.append({"name": kind, "ph": "i", "s": "t",
                           "cat": "request", "pid": 2, "tid": tid,
                           "ts": ts / 1e3, "args": detail or {}})
                if kind in _FLOW_KINDS:
                    # flow: request lifecycle -> the engine-step track
                    ev.append({"name": "engine-step", "ph": "s",
                               "cat": "flow", "id": flow_id, "pid": 2,
                               "tid": tid, "ts": ts / 1e3})
                    ev.append({"name": "engine-step", "ph": "f",
                               "bp": "e", "cat": "flow", "id": flow_id,
                               "pid": 1, "tid": 0, "ts": ts / 1e3})
                    flow_id += 1
            ev.append({"name": name, "ph": "E", "cat": "request",
                       "pid": 2, "tid": tid, "ts": last_ts / 1e3})
        for e in host:
            ev.append({"name": e.name, "ph": "X", "cat": "host",
                       "pid": 3, "tid": e.tid % (1 << 31),
                       "ts": e.start_ns / 1e3,
                       "dur": max(0, e.end_ns - e.start_ns) / 1e3})
        # stable ts sort: equal-ts events keep insertion order, so each
        # request's B precedes its instants precedes its E
        ev.sort(key=lambda e: e["ts"])
        return {"traceEvents": ev, "displayTimeUnit": "ms",
                "otherData": {
                    "generator": "paddle_tpu.monitor.trace",
                    "capture_start_ns": self._started_ns,
                    "capture_stop_ns": self._stopped_ns}}


def validate_chrome_trace(payload) -> List[str]:
    """Best-effort trace-event-schema check shared by the tests and
    ``tools/trace_capture.py``: JSON-ability, required keys per event,
    non-decreasing ``ts``, and matched B/E pairs per (pid, tid) stack.
    Returns a list of problems (empty = valid)."""
    problems: List[str] = []
    try:
        payload = json.loads(json.dumps(payload))
    except (TypeError, ValueError) as e:
        return [f"not JSON-serializable: {e}"]
    events = payload.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    last_ts = None
    stacks: Dict[Tuple[int, int], List[str]] = {}
    for i, e in enumerate(events):
        for key in ("ph", "ts", "pid", "tid"):
            if key not in e:
                problems.append(f"event {i} missing {key!r}: {e}")
                break
        else:
            if "name" not in e and e["ph"] not in ("s", "t", "f"):
                problems.append(f"event {i} missing 'name': {e}")
            ts = e["ts"]
            if last_ts is not None and ts < last_ts:
                problems.append(
                    f"event {i} ts {ts} < previous {last_ts} — "
                    "timestamps must be non-decreasing")
            last_ts = ts
            key = (e["pid"], e["tid"])
            if e["ph"] == "B":
                stacks.setdefault(key, []).append(e.get("name", ""))
            elif e["ph"] == "E":
                stack = stacks.get(key)
                if not stack:
                    problems.append(
                        f"event {i}: E with no open B on track {key}")
                else:
                    stack.pop()
            elif e["ph"] == "X" and "dur" not in e:
                problems.append(f"event {i}: X event missing 'dur'")
    for key, stack in stacks.items():
        if stack:
            problems.append(f"unclosed B event(s) {stack} on track {key}")
    return problems


_tracer = Tracer()


def get_tracer() -> Tracer:
    return _tracer


def start_capture(**kwargs) -> None:
    _tracer.start_capture(**kwargs)


def stop_capture() -> None:
    _tracer.stop_capture()


def request_timeline(request_id: str) -> Optional[dict]:
    return _tracer.request_timeline(request_id)


def export_chrome_trace(path: Optional[str] = None) -> dict:
    """The capture buffer as chrome-trace JSON; optionally written to
    ``path`` (load it in Perfetto / chrome://tracing)."""
    payload = _tracer.to_chrome_trace()
    if path:
        with open(path, "w") as f:
            json.dump(payload, f)
    return payload
