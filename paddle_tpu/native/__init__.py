"""Native (C++) runtime components.

The reference keeps its runtime services (event recorder, stores, readers) in
C++ (reference: paddle/phi/api/profiler/host_event_recorder.h:231,
paddle/phi/core/distributed/store/tcp_store.cc); here each service is a small
C++ shared library with a C ABI, loaded via ctypes.  Libraries are compiled
on first use with g++ and cached by source hash, so the package needs no build
step to install; every consumer must degrade gracefully to a pure-Python
fallback when no toolchain is present.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def load_native(name: str, extra_flags: tuple = ()) -> ctypes.CDLL:
    """Compile ``<name>.cc`` into a shared library (cached) and dlopen it."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_SRC_DIR, name + ".cc")
        with open(src, "rb") as f:
            blob = f.read()
        tag = hashlib.sha256(blob + repr(extra_flags).encode()).hexdigest()[:16]
        os.makedirs(_BUILD_DIR, exist_ok=True)
        out = os.path.join(_BUILD_DIR, f"lib{name}-{tag}.so")
        if not os.path.exists(out):
            cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared",
                   "-pthread", src, "-o", out + ".tmp", *extra_flags]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except (subprocess.CalledProcessError, OSError) as e:
                msg = getattr(e, "stderr", str(e))
                raise NativeBuildError(f"building {name}: {msg}") from e
            os.replace(out + ".tmp", out)
        lib = ctypes.CDLL(out)
        _cache[name] = lib
        return lib
