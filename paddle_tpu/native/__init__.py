"""Native (C++) runtime components.

The reference keeps its runtime services (event recorder, stores, readers) in
C++ (reference: paddle/phi/api/profiler/host_event_recorder.h:231,
paddle/phi/core/distributed/store/tcp_store.cc); here each service is a small
C++ shared library with a C ABI, loaded via ctypes.  Libraries are compiled
on first use with g++ and cached by source hash, so the package needs no build
step to install; every consumer must degrade gracefully to a pure-Python
fallback when no toolchain is present.

``build_shared`` is the single compile/cache pipeline — also used by
utils.cpp_extension for user extensions — guarded by an in-process lock plus
an flock so concurrent processes never corrupt the cache.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional, Sequence

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD_DIR = os.path.join(_SRC_DIR, "_build")
_lock = threading.Lock()
_cache: dict = {}


class NativeBuildError(RuntimeError):
    pass


def _hash_sources(sources: Sequence[str], extra_flags: Sequence[str]) -> str:
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    # headers are not tracked through #include; approximate by hashing any
    # header files sitting in -I directories so header edits trigger rebuilds
    for flag in extra_flags or ():
        if flag.startswith("-I"):
            inc = flag[2:]
            if os.path.isdir(inc):
                for root, _dirs, files in sorted(os.walk(inc)):
                    for fn in sorted(files):
                        if fn.endswith((".h", ".hpp", ".hh", ".cuh")):
                            with open(os.path.join(root, fn), "rb") as f:
                                h.update(f.read())
    h.update(repr(tuple(extra_flags or ())).encode())
    return h.hexdigest()[:16]


def build_shared(name: str, sources: Sequence[str],
                 extra_flags: Sequence[str] = (),
                 build_dir: Optional[str] = None,
                 verbose: bool = False) -> str:
    """Compile ``sources`` into a cached shared library; returns its path.
    Safe under concurrent calls from multiple processes (flock) and threads
    (module lock taken by callers holding _lock or via load_native)."""
    root = build_dir or _BUILD_DIR
    os.makedirs(root, exist_ok=True)
    tag = _hash_sources(sources, extra_flags)
    out = os.path.join(root, f"lib{name}-{tag}.so")
    if os.path.exists(out):
        return out
    lock_path = out + ".lock"
    import fcntl
    with open(lock_path, "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if os.path.exists(out):   # built by the lock holder before us
                return out
            tmp = f"{out}.tmp.{os.getpid()}"
            cmd = ["g++", "-std=c++17", "-O2", "-fPIC", "-shared",
                   "-pthread", *map(str, sources), *list(extra_flags or ()),
                   "-o", tmp]
            if verbose:
                print("building:", " ".join(cmd))
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               text=True)
            except (subprocess.CalledProcessError, OSError) as e:
                msg = getattr(e, "stderr", str(e))
                raise NativeBuildError(f"building {name}: {msg}") from e
            os.replace(tmp, out)
            return out
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)


def load_native(name: str, extra_flags: tuple = ()) -> ctypes.CDLL:
    """Compile ``<name>.cc`` (cached) and dlopen it."""
    with _lock:
        if name in _cache:
            return _cache[name]
        src = os.path.join(_SRC_DIR, name + ".cc")
        out = build_shared(name, [src], extra_flags)
        lib = ctypes.CDLL(out)
        _cache[name] = lib
        return lib
