// Host event recorder: thread-local append-only buffers; the hot path takes
// only the owning thread's (uncontended) mutex, never a global lock.
//
// Capability parity with the reference's HostEventRecorder
// (reference: paddle/phi/api/profiler/host_event_recorder.h:205,231 —
// thread-local EventContainer chunks gathered on demand).  TPU-native: device
// timelines come from XLA/jax.profiler; this recorder owns only host spans,
// which the Python layer merges into one chrome trace.
//
// Collection is two-phase and atomic w.r.t. concurrent pushes:
//   pt_drain()  — moves every thread's events into a global staging area
//                 (per-buffer lock) and returns the staged count;
//   pt_read(..) — copies staged events out and clears the staging area.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>
#include <utility>
#include <vector>

namespace {

struct Event {
  uint32_t name_id;
  uint64_t tid;
  uint64_t start_ns;
  uint64_t end_ns;
};

struct ThreadBuffer {
  uint64_t tid = 0;
  std::mutex mu;  // owner thread vs. draining thread
  std::vector<Event> events;
};

std::mutex g_mu;  // guards buffer/name registries + staging
std::vector<ThreadBuffer*> g_buffers;
std::unordered_map<std::string, uint32_t> g_name_ids;
std::vector<std::string> g_names;
std::vector<Event> g_staging;
std::atomic<int> g_enabled{0};

thread_local ThreadBuffer* t_buf = nullptr;

ThreadBuffer* LocalBuffer() {
  if (t_buf == nullptr) {
    auto* b = new ThreadBuffer();
    b->tid = static_cast<uint64_t>(
        std::hash<std::thread::id>()(std::this_thread::get_id()));
    b->events.reserve(1024);
    std::lock_guard<std::mutex> l(g_mu);
    g_buffers.push_back(b);
    t_buf = b;
  }
  return t_buf;
}

uint64_t NowNs() {
  return static_cast<uint64_t>(
      std::chrono::duration_cast<std::chrono::nanoseconds>(
          std::chrono::steady_clock::now().time_since_epoch())
          .count());
}

}  // namespace

extern "C" {

void pt_tracer_enable(int on) { g_enabled.store(on ? 1 : 0); }

int pt_tracer_enabled() { return g_enabled.load(std::memory_order_relaxed); }

uint64_t pt_now_ns() { return NowNs(); }

uint32_t pt_register_name(const char* name) {
  std::lock_guard<std::mutex> l(g_mu);
  auto it = g_name_ids.find(name);
  if (it != g_name_ids.end()) return it->second;
  uint32_t id = static_cast<uint32_t>(g_names.size());
  g_names.emplace_back(name);
  g_name_ids.emplace(name, id);
  return id;
}

void pt_push_event(uint32_t name_id, uint64_t start_ns, uint64_t end_ns) {
  if (!g_enabled.load(std::memory_order_relaxed)) return;
  ThreadBuffer* b = LocalBuffer();
  std::lock_guard<std::mutex> l(b->mu);
  b->events.push_back(Event{name_id, b->tid, start_ns, end_ns});
}

uint64_t pt_drain() {
  std::lock_guard<std::mutex> g(g_mu);
  for (auto* b : g_buffers) {
    std::lock_guard<std::mutex> l(b->mu);
    if (b->events.empty()) continue;
    g_staging.insert(g_staging.end(), b->events.begin(), b->events.end());
    b->events.clear();
  }
  return g_staging.size();
}

uint64_t pt_read(uint32_t* name_ids, uint64_t* tids, uint64_t* starts,
                 uint64_t* ends, uint64_t cap) {
  std::lock_guard<std::mutex> g(g_mu);
  uint64_t n = g_staging.size() < cap ? g_staging.size() : cap;
  for (uint64_t i = 0; i < n; ++i) {
    const Event& e = g_staging[i];
    name_ids[i] = e.name_id;
    tids[i] = e.tid;
    starts[i] = e.start_ns;
    ends[i] = e.end_ns;
  }
  g_staging.erase(g_staging.begin(), g_staging.begin() + n);
  return n;
}

const char* pt_name(uint32_t id) {
  std::lock_guard<std::mutex> l(g_mu);
  if (id >= g_names.size()) return "";
  return g_names[id].c_str();
}

}  // extern "C"
