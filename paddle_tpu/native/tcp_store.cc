// TCP key-value store for host-side rendezvous/coordination.
//
// Capability parity with the reference's TCPStore
// (reference: paddle/phi/core/distributed/store/tcp_store.cc — master server
// with set/get/add/wait, worker clients over TCP).  TPU-native role: inside a
// slice, rendezvous is jax.distributed's coordination service; this store
// covers the *framework-level* coordination the reference exposes to users
// (elastic membership, launch barriers, cross-host handshakes) without
// pulling in etcd/brpc.
//
// Wire protocol (little-endian):
//   request : u8 cmd | u32 klen | key bytes | payload
//     cmd 0 SET  : payload = u32 vlen | value bytes        -> resp u8 0
//     cmd 1 GET  : payload = i64 timeout_ms                -> resp u32 vlen
//                  (0xFFFFFFFF on timeout) | value bytes
//     cmd 2 ADD  : payload = i64 delta                     -> resp i64 new
//     cmd 3 WAIT : payload = i64 timeout_ms                -> resp u8 0|1
//     cmd 4 CHECK: no payload                              -> resp u8 0|1
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct Server {
  int listen_fd = -1;
  std::atomic<bool> stop{false};
  std::thread accept_thread;
  std::vector<std::thread> conn_threads;
  std::mutex conn_mu;
  std::vector<int> conn_fds;   // open connections, shut down on stop
  std::mutex mu;
  std::condition_variable cv;
  std::map<std::string, std::string> data;
};

bool ReadFull(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

bool WriteFull(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n > 0) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= static_cast<size_t>(r);
  }
  return true;
}

void HandleConn(Server* s, int fd) {
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  for (;;) {
    uint8_t cmd;
    uint32_t klen;
    if (!ReadFull(fd, &cmd, 1) || !ReadFull(fd, &klen, 4)) break;
    if (klen > (1u << 20)) break;
    std::string key(klen, '\0');
    if (!ReadFull(fd, key.data(), klen)) break;

    if (cmd == 0) {  // SET
      uint32_t vlen;
      if (!ReadFull(fd, &vlen, 4) || vlen > (1u << 28)) break;
      std::string val(vlen, '\0');
      if (!ReadFull(fd, val.data(), vlen)) break;
      {
        std::lock_guard<std::mutex> l(s->mu);
        s->data[key] = std::move(val);
      }
      s->cv.notify_all();
      uint8_t ok = 0;
      if (!WriteFull(fd, &ok, 1)) break;
    } else if (cmd == 1 || cmd == 3) {  // GET / WAIT (blocking)
      int64_t timeout_ms;
      if (!ReadFull(fd, &timeout_ms, 8)) break;
      std::string val;
      bool found = false;
      {
        std::unique_lock<std::mutex> l(s->mu);
        auto pred = [&] {
          return s->stop.load() || s->data.count(key) != 0;
        };
        if (timeout_ms < 0) {
          s->cv.wait(l, pred);
        } else {
          s->cv.wait_for(l, std::chrono::milliseconds(timeout_ms), pred);
        }
        auto it = s->data.find(key);
        if (it != s->data.end()) {
          found = true;
          val = it->second;
        }
      }
      if (cmd == 1) {
        uint32_t vlen = found ? static_cast<uint32_t>(val.size())
                              : 0xFFFFFFFFu;
        if (!WriteFull(fd, &vlen, 4)) break;
        if (found && !WriteFull(fd, val.data(), val.size())) break;
      } else {
        uint8_t rc = found ? 0 : 1;
        if (!WriteFull(fd, &rc, 1)) break;
      }
    } else if (cmd == 2) {  // ADD
      int64_t delta;
      if (!ReadFull(fd, &delta, 8)) break;
      int64_t result;
      {
        std::lock_guard<std::mutex> l(s->mu);
        int64_t cur = 0;
        auto it = s->data.find(key);
        if (it != s->data.end() && it->second.size() == 8) {
          std::memcpy(&cur, it->second.data(), 8);
        }
        result = cur + delta;
        std::string v(8, '\0');
        std::memcpy(v.data(), &result, 8);
        s->data[key] = std::move(v);
      }
      s->cv.notify_all();
      if (!WriteFull(fd, &result, 8)) break;
    } else if (cmd == 4) {  // CHECK
      uint8_t exists;
      {
        std::lock_guard<std::mutex> l(s->mu);
        exists = s->data.count(key) ? 1 : 0;
      }
      if (!WriteFull(fd, &exists, 1)) break;
    } else {
      break;
    }
  }
  {
    // drop from the live set before closing so server stop never
    // shutdown()s a recycled fd number
    std::lock_guard<std::mutex> l(s->conn_mu);
    for (auto it = s->conn_fds.begin(); it != s->conn_fds.end(); ++it) {
      if (*it == fd) {
        s->conn_fds.erase(it);
        break;
      }
    }
  }
  ::close(fd);
}

void AcceptLoop(Server* s) {
  for (;;) {
    int fd = ::accept(s->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (s->stop.load()) return;
      // persistent errors (EMFILE, ...) must not busy-spin
      std::this_thread::sleep_for(std::chrono::milliseconds(10));
      continue;
    }
    {
      std::lock_guard<std::mutex> l(s->conn_mu);
      s->conn_fds.push_back(fd);
    }
    s->conn_threads.emplace_back(HandleConn, s, fd);
  }
}

}  // namespace

extern "C" {

// Starts a server on `port` (0 = ephemeral).  Returns handle, writes the
// bound port into *out_port; nullptr on failure.
void* pt_store_server_start(int port, int* out_port) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return nullptr;
  int one = 1;
  setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0 ||
      ::listen(fd, 128) != 0) {
    ::close(fd);
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  ::getsockname(fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  if (out_port) *out_port = ntohs(addr.sin_port);
  auto* s = new Server();
  s->listen_fd = fd;
  s->accept_thread = std::thread(AcceptLoop, s);
  return s;
}

void pt_store_server_stop(void* handle) {
  auto* s = static_cast<Server*>(handle);
  s->stop.store(true);
  s->cv.notify_all();
  ::shutdown(s->listen_fd, SHUT_RDWR);
  ::close(s->listen_fd);
  if (s->accept_thread.joinable()) s->accept_thread.join();
  {
    // unblock HandleConn threads sitting in recv() on live clients
    std::lock_guard<std::mutex> l(s->conn_mu);
    for (int fd : s->conn_fds) ::shutdown(fd, SHUT_RDWR);
  }
  for (auto& t : s->conn_threads) {
    if (t.joinable()) t.join();
  }
  delete s;
}

// Client: one blocking connection.
int pt_store_connect(const char* host, int port, int timeout_ms) {
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  char portstr[16];
  std::snprintf(portstr, sizeof(portstr), "%d", port);
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::milliseconds(timeout_ms);
  for (;;) {
    if (getaddrinfo(host, portstr, &hints, &res) == 0) {
      int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
      if (fd >= 0 &&
          ::connect(fd, res->ai_addr, res->ai_addrlen) == 0) {
        freeaddrinfo(res);
        int one = 1;
        setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return fd;
      }
      if (fd >= 0) ::close(fd);
      freeaddrinfo(res);
      res = nullptr;
    }
    if (std::chrono::steady_clock::now() >= deadline) return -1;
    std::this_thread::sleep_for(std::chrono::milliseconds(50));
  }
}

void pt_store_close(int fd) { ::close(fd); }

static bool SendKey(int fd, uint8_t cmd, const char* key) {
  uint32_t klen = static_cast<uint32_t>(std::strlen(key));
  return WriteFull(fd, &cmd, 1) && WriteFull(fd, &klen, 4) &&
         WriteFull(fd, key, klen);
}

int pt_store_set(int fd, const char* key, const void* val, uint32_t vlen) {
  if (!SendKey(fd, 0, key) || !WriteFull(fd, &vlen, 4) ||
      !WriteFull(fd, val, vlen))
    return -1;
  uint8_t ok;
  return ReadFull(fd, &ok, 1) ? 0 : -1;
}

// Returns value length, -1 on timeout/error.  Caller provides buf/cap; if
// the value is larger than cap the first cap bytes are stored (check the
// returned length).
int64_t pt_store_get(int fd, const char* key, int64_t timeout_ms, void* buf,
                     uint32_t cap) {
  if (!SendKey(fd, 1, key) || !WriteFull(fd, &timeout_ms, 8)) return -1;
  uint32_t vlen;
  if (!ReadFull(fd, &vlen, 4)) return -1;
  if (vlen == 0xFFFFFFFFu) return -1;
  std::string val(vlen, '\0');
  if (!ReadFull(fd, val.data(), vlen)) return -1;
  std::memcpy(buf, val.data(), vlen < cap ? vlen : cap);
  return static_cast<int64_t>(vlen);
}

int64_t pt_store_add(int fd, const char* key, int64_t delta) {
  if (!SendKey(fd, 2, key) || !WriteFull(fd, &delta, 8)) return INT64_MIN;
  int64_t result;
  return ReadFull(fd, &result, 8) ? result : INT64_MIN;
}

int pt_store_wait(int fd, const char* key, int64_t timeout_ms) {
  if (!SendKey(fd, 3, key) || !WriteFull(fd, &timeout_ms, 8)) return -1;
  uint8_t rc;
  return ReadFull(fd, &rc, 1) ? rc : -1;
}

int pt_store_check(int fd, const char* key) {
  if (!SendKey(fd, 4, key)) return -1;
  uint8_t rc;
  return ReadFull(fd, &rc, 1) ? rc : -1;
}

}  // extern "C"
