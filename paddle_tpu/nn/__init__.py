"""paddle_tpu.nn — layers, functional, initializers, clip.

Capability parity: python/paddle/nn/ (~150 layers in the reference; the
high-traffic surface is implemented, organized the same way).
"""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401

from .layer.layers import (  # noqa: F401
    Layer, ParamAttr, Sequential, LayerList, LayerDict, ParameterList,
    ParameterDict, Identity,
)
from .layer.common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Unflatten, Pad1D, Pad2D, Pad3D, ZeroPad2D, Upsample,
    UpsamplingBilinear2D, UpsamplingNearest2D, PixelShuffle, PixelUnshuffle,
    Bilinear, CosineSimilarity, Unfold, Fold, MaxUnPool2D, ChannelShuffle,
    SpectralNorm, ZeroPad1D, ZeroPad3D, PairwiseDistance, FeatureAlphaDropout,
)
from .layer.conv_pool import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv2DTranspose, MaxPool1D, MaxPool2D, AvgPool1D,
    AvgPool2D, AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveMaxPool2D,
    Conv1DTranspose, Conv3DTranspose, MaxPool3D, AvgPool3D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool3D, LPPool1D, LPPool2D,
    FractionalMaxPool2D, FractionalMaxPool3D, MaxUnPool1D, MaxUnPool3D,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D,
    InstanceNorm3D, LocalResponseNorm,
)
from .layer.activation import (  # noqa: F401
    CELU, ELU, GELU, GLU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
    SELU, Sigmoid, Silu, Softmax, Softplus, Softshrink, Softsign, Swish,
    Tanh, Tanhshrink, ThresholdedReLU, RReLU, Softmax2D,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    KLDivLoss, SmoothL1Loss, HuberLoss, MarginRankingLoss,
    HingeEmbeddingLoss, CosineEmbeddingLoss, TripletMarginLoss,
    CTCLoss, GaussianNLLLoss, PoissonNLLLoss, SoftMarginLoss,
    MultiLabelSoftMarginLoss, MultiMarginLoss, TripletMarginWithDistanceLoss,
    HSigmoidLoss, RNNTLoss, AdaptiveLogSoftmaxWithLoss,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, LSTMCell, GRUCell, SimpleRNNCell, RNNBase,
    RNN, BiRNN, RNNCellBase,
)
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
from . import quant  # noqa: F401  (quantization layers, SURVEY #70)
from .decode import Decoder, BeamSearchDecoder, dynamic_decode  # noqa: F401
