"""Gradient clipping strategies.

Capability parity: python/paddle/nn/clip.py in the reference
(ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm), consumed by the
optimizer's grad_clip hook.
"""
from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp

from ..framework.tensor import Tensor, wrap_array


class ClipGradBase:
    def __call__(self, params_grads: List[Tuple[Tensor, Tensor]]):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, wrap_array(jnp.clip(g._data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, wrap_array((g._data * scale).astype(g._data.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """reference: ClipGradByGlobalNorm (nn/clip.py) — the hybrid-parallel
    variant that reduces the norm across model-parallel groups lives in
    distributed/fleet (HybridParallelClipGrad)."""

    def __init__(self, clip_norm, group_name="default_group",
                 auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        sq = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            sq.append(jnp.sum(jnp.square(g._data.astype(jnp.float32))))
        if not sq:
            return params_grads
        global_norm = jnp.sqrt(sum(sq))
        scale = jnp.minimum(self.clip_norm / jnp.maximum(global_norm, 1e-12),
                            1.0)
        out = []
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                out.append((p, g))
                continue
            out.append((p, wrap_array((g._data * scale).astype(g._data.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return wrap_array(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data)) for g in grads]))
    else:
        total = jnp.power(sum(jnp.sum(jnp.power(jnp.abs(g._data), norm_type))
                              for g in grads), 1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for g in grads:
        g._data = (g._data * scale).astype(g._data.dtype)
    return wrap_array(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -clip_value, clip_value)
