"""Seq2seq decoding: Decoder / BeamSearchDecoder / dynamic_decode.

Capability parity: python/paddle/nn/decode.py (Decoder:50,
BeamSearchDecoder:161, dynamic_decode:1238).

TPU-native note: the decode loop is a host-side Python loop (steps are
data-dependent on `finished`), but every step's beam expansion, top-k and
state gather run as one fused XLA computation on device; the final
backtrace is the compiled ``gather_tree`` op.  This matches the
reference's dygraph path (decode.py: while loop over decoder.step).
"""
from __future__ import annotations

import collections

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.tensor import Tensor, wrap_array
from . import functional as F

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _tree_map(fn, tree):
    if isinstance(tree, (tuple, list)):
        return type(tree)(_tree_map(fn, t) for t in tree)
    return fn(tree)


class Decoder:
    """reference: nn/decode.py:50 — the step-decoder interface:
    ``initialize() -> (inputs, states, finished)``,
    ``step(time, inputs, states) -> (outputs, states, inputs, finished)``,
    ``finalize(outputs, states, lengths)``."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """reference: nn/decode.py:161 — beam search over an RNN cell.

    cell: a cell Layer ``(inputs, states) -> (outputs, new_states)``.
    embedding_fn: token ids -> embeddings for the next step's inputs.
    output_fn: projects cell output to vocab logits (e.g. a Linear).
    """

    OutputWrapper = collections.namedtuple(
        "OutputWrapper", ("scores", "predicted_ids", "parent_ids"))
    StateWrapper = collections.namedtuple(
        "StateWrapper", ("cell_states", "log_probs", "finished", "lengths"))

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- helpers ----------------------------------------------------------
    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """[B, ...] -> [B*beam, ...] by repeating each batch row."""
        a = _arr(x)
        tiled = jnp.repeat(a[:, None], beam_size, axis=1)
        return wrap_array(tiled.reshape((-1,) + a.shape[1:]))

    def _merge(self, a):        # [B, beam, ...] -> [B*beam, ...]
        return a.reshape((-1,) + a.shape[2:])

    def _split(self, a):        # [B*beam, ...] -> [B, beam, ...]
        return a.reshape((-1, self.beam_size) + a.shape[1:])

    # -- Decoder interface ------------------------------------------------
    def initialize(self, initial_cell_states):
        states = _tree_map(
            lambda t: _arr(self.tile_beam_merge_with_batch(
                t, self.beam_size)), initial_cell_states)
        bxk = jax.tree_util.tree_leaves(states)[0].shape[0]
        batch = bxk // self.beam_size
        start = jnp.full((bxk,), self.start_token, jnp.int32)
        inputs = self.embedding_fn(wrap_array(start)) \
            if self.embedding_fn is not None else wrap_array(start)
        # beam 0 live, the rest dead (standard first-step symmetry break)
        log_probs = jnp.tile(
            jnp.array([0.0] + [-1e9] * (self.beam_size - 1), jnp.float32),
            (batch, 1))
        finished = jnp.zeros((batch, self.beam_size), bool)
        lengths = jnp.zeros((batch, self.beam_size), jnp.int32)
        state = self.StateWrapper(states, log_probs, finished, lengths)
        return inputs, state, wrap_array(finished)

    def step(self, time, inputs, states, **kwargs):
        cell_out, cell_states = self.cell(
            inputs, _tree_map(wrap_array, states.cell_states), **kwargs)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        logits = _arr(cell_out)                       # [B*beam, V]
        V = logits.shape[-1]
        lp = jax.nn.log_softmax(logits, axis=-1)
        lp = self._split(lp)                          # [B, beam, V]
        prev = states.log_probs[..., None]            # [B, beam, 1]
        # finished beams only propagate through end_token with prob 1
        fin = states.finished[..., None]
        onehot_end = (jnp.arange(V) == self.end_token)
        masked = jnp.where(onehot_end, 0.0, -1e9)
        total = jnp.where(fin, prev + masked, prev + lp)   # [B, beam, V]
        flat = total.reshape(total.shape[0], -1)           # [B, beam*V]
        top_val, top_idx = jax.lax.top_k(flat, self.beam_size)
        parent = (top_idx // V).astype(jnp.int32)          # [B, beam]
        token = (top_idx % V).astype(jnp.int32)

        batch = flat.shape[0]
        brow = jnp.arange(batch)[:, None]

        def gather_state(s):
            split = self._split(s)                          # [B, beam, ...]
            return self._merge(split[brow, parent])

        next_cell = _tree_map(lambda t: gather_state(_arr(t)), cell_states)
        was_fin = states.finished[brow, parent]
        now_fin = was_fin | (token == self.end_token)
        lengths = states.lengths[brow, parent] + (~was_fin).astype(jnp.int32)

        next_state = self.StateWrapper(next_cell, top_val, now_fin, lengths)
        outputs = self.OutputWrapper(wrap_array(top_val),
                                     wrap_array(token),
                                     wrap_array(parent))
        flat_token = token.reshape(-1)
        next_inputs = self.embedding_fn(wrap_array(flat_token)) \
            if self.embedding_fn is not None else wrap_array(flat_token)
        return outputs, next_state, next_inputs, wrap_array(now_fin)

    def finalize(self, outputs, final_states, sequence_lengths):
        """Backtrace parents to full sequences: [B, T, beam] ids."""
        ids = jnp.stack([_arr(o.predicted_ids) for o in outputs])  # [T,B,K]
        parents = jnp.stack([_arr(o.parent_ids) for o in outputs])
        full = F.gather_tree(wrap_array(ids), wrap_array(parents))
        return full, final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """reference: nn/decode.py:1238 — run ``decoder`` until every lane
    finishes or ``max_step_num`` steps elapse."""
    inputs, states, finished = decoder.initialize(inits)
    outputs = []
    step = 0
    limit = int(max_step_num) if max_step_num is not None else None
    while limit is None or step < limit:
        out, states, inputs, finished = decoder.step(step, inputs, states,
                                                     **kwargs)
        outputs.append(out)
        step += 1
        if bool(jnp.all(_arr(finished))):
            break
    lengths = getattr(states, "lengths", None)
    final, final_states = decoder.finalize(outputs, states, lengths)
    if isinstance(final, Tensor) and not output_time_major:
        final = wrap_array(jnp.moveaxis(_arr(final), 0, 1))  # [B, T, beam]
    if return_length:
        return final, final_states, wrap_array(lengths) \
            if lengths is not None else None
    return final, final_states
