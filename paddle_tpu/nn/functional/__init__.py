"""nn functional ops.

Capability parity: python/paddle/nn/functional/ in the reference (activation,
conv, pooling, norm, loss, attention; flash_attention.py:364).

TPU-native: convs/matmuls go straight to lax (MXU); flash attention has a
Pallas kernel (paddle_tpu/ops/pallas/flash_attention.py) with an XLA fallback;
dropout draws from the stateful Generator facade.
"""
from __future__ import annotations

import builtins
import math as pymath
from typing import Optional, Sequence, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.dispatch import def_op, call_op
from ...framework.tensor import Tensor
from ...framework import dtype as dtypes
from ...framework import random as _random

# ------------------------------------------------------------- activations
_ACT = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "silu": jax.nn.silu,
    "swish": jax.nn.silu,
    "mish": jax.nn.mish,
    "softsign": jax.nn.soft_sign,
    "tanhshrink": lambda x: x - jnp.tanh(x),
    "hardswish": jax.nn.hard_swish,
    "hardsigmoid": lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0),
}
_g = globals()
for _name, _fn in _ACT.items():
    _g[_name] = def_op(_name)(_fn)


@def_op("gelu")
def gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


@def_op("leaky_relu")
def leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope=negative_slope)


@def_op("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


@def_op("celu")
def celu(x, alpha=1.0):
    return jax.nn.celu(x, alpha=alpha)


@def_op("selu")
def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772):
    return scale * jnp.where(x > 0, x, alpha * jnp.expm1(x))


@def_op("hardtanh")
def hardtanh(x, min=-1.0, max=1.0):
    return jnp.clip(x, min, max)


@def_op("hardshrink")
def hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


@def_op("softshrink")
def softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold,
                     jnp.where(x < -threshold, x + threshold, 0.0))


@def_op("softplus")
def softplus(x, beta=1.0, threshold=20.0):
    scaled = beta * x
    return jnp.where(scaled > threshold, x, jax.nn.softplus(scaled) / beta)


@def_op("prelu")
def prelu(x, weight, data_format="NCHW"):
    w = weight
    if w.size > 1:
        shape = [1] * x.ndim
        ch_dim = 1 if data_format.startswith("NC") else x.ndim - 1
        shape[ch_dim] = w.size
        w = w.reshape(shape)
    return jnp.where(x > 0, x, w * x)


@def_op("softmax_")
def _softmax(x, axis):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtypes.convert_dtype(dtype))
    return _softmax(x, int(axis))


@def_op("log_softmax_")
def _log_softmax(x, axis):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        x = x.astype(dtypes.convert_dtype(dtype))
    return _log_softmax(x, int(axis))


@def_op("gumbel_softmax")
def _gumbel_softmax(x, key, temperature, hard):
    g = jax.random.gumbel(key, x.shape, x.dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=-1)
    if hard:
        idx = jnp.argmax(y, axis=-1, keepdims=True)
        y_hard = jnp.zeros_like(y).at[
            tuple(jnp.meshgrid(*[jnp.arange(s) for s in y.shape[:-1]],
                               indexing="ij")) + (idx[..., 0],)].set(1.0)
        y = y_hard + y - lax.stop_gradient(y)
    return y


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    return _gumbel_softmax(x, _random.split_key(), temperature, hard)


@def_op("glu")
def glu(x, axis=-1):
    a, b = jnp.split(x, 2, axis=axis)
    return a * jax.nn.sigmoid(b)


@def_op("maxout")
def maxout(x, groups, axis=1):
    shape = list(x.shape)
    shape[axis] = shape[axis] // groups
    shape.insert(axis + 1, groups)
    return jnp.max(x.reshape(shape), axis=axis + 1)


@def_op("normalize")
def normalize(x, p=2, axis=1, epsilon=1e-12):
    nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=True),
                    1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


@def_op("label_smooth")
def label_smooth(label, prior_dist=None, epsilon=0.1):
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * prior_dist
    return (1 - epsilon) * label + epsilon / k


# ---------------------------------------------------------------- dropout
@def_op("dropout_")
def _dropout(x, key, p, training, mode, axis):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    if axis is not None:
        shape = [1] * x.ndim
        for a in (axis if isinstance(axis, (list, tuple)) else [axis]):
            shape[a] = x.shape[a]
        keep = jax.random.bernoulli(key, 1.0 - p, tuple(shape))
    else:
        keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), 0.0).astype(x.dtype)
    return jnp.where(keep, x, 0.0).astype(x.dtype)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if isinstance(p, Tensor):
        p = float(p.item())
    # the key is split ONLY when randomness will actually be consumed:
    # eval-mode graphs must not fold RNG keys (it breaks key-sequence
    # determinism and drags PRNG ops into exported/traced graphs); the
    # eval/p==0 semantics themselves live in _dropout, one place
    key = _random.split_key() if (training and p != 0) else None
    return _dropout(x, key, p, training, mode, axis)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x * 1.0
    alpha = -1.7580993408473766

    def _fn(x, key):
        keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
        a = ((1 - p) * (1 + p * alpha ** 2)) ** -0.5
        b = -a * alpha * p
        return (a * jnp.where(keep, x, alpha) + b).astype(x.dtype)
    return call_op("alpha_dropout", _fn, (x, _random.split_key()), {})


# ------------------------------------------------------------------ linear
@def_op("linear")
def linear(x, weight, bias=None):
    # paddle weight layout: [in, out] (reference: nn/functional/common.py linear)
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


@def_op("embedding_")
def _embedding(weight, x, padding_idx):
    out = jnp.take(weight, x, axis=0)
    if padding_idx is not None:
        mask = (x == padding_idx)[..., None]
        out = jnp.where(mask, 0.0, out)
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    return _embedding(weight, x, padding_idx)


@def_op("one_hot_f")
def _onehot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=jnp.float32)


def one_hot(x, num_classes, name=None):
    return _onehot(x, int(num_classes))


@def_op("bilinear")
def bilinear(x1, x2, weight, bias=None):
    # weight: [out, in1, in2]
    out = jnp.einsum("bi,oij,bj->bo", x1, weight, x2)
    if bias is not None:
        out = out + bias
    return out


@def_op("cosine_similarity")
def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


# ------------------------------------------------------------------- convs
def _conv_dn(ndim, channel_last):
    if ndim == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if ndim == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _norm_tuple(v, n):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    return tuple(int(x) for x in v)


def _conv_padding(padding, n):
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, (int, np.integer)):
        return [(int(padding),) * 2] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, (int, np.integer)) for p in padding):
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * n:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(n)]
    return [tuple(p) for p in padding]


def _conv_impl(x, weight, bias, stride, padding, dilation, groups, ndim,
               channel_last):
    dn = lax.conv_dimension_numbers(x.shape, weight.shape,
                                    _conv_dn(ndim, channel_last))
    out = lax.conv_general_dilated(
        x, weight,
        window_strides=_norm_tuple(stride, ndim),
        padding=_conv_padding(padding, ndim),
        rhs_dilation=_norm_tuple(dilation, ndim),
        dimension_numbers=dn,
        feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        shape = [1] * out.ndim
        shape[out.ndim - 1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


@def_op("conv1d")
def _conv1d(x, weight, bias, stride, padding, dilation, groups, channel_last):
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups, 1,
                      channel_last)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv1d(x, weight, bias, stride, padding, dilation, groups,
                   data_format in ("NLC",))


@def_op("conv2d")
def _conv2d(x, weight, bias, stride, padding, dilation, groups, channel_last):
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups, 2,
                      channel_last)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """reference: paddle.nn.functional.conv2d; weight layout [out, in/g, kh, kw]."""
    return _conv2d(x, weight, bias, stride, padding, dilation, groups,
                   data_format == "NHWC")


@def_op("conv3d")
def _conv3d(x, weight, bias, stride, padding, dilation, groups, channel_last):
    return _conv_impl(x, weight, bias, stride, padding, dilation, groups, 3,
                      channel_last)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv3d(x, weight, bias, stride, padding, dilation, groups,
                   data_format == "NDHWC")


@def_op("conv2d_transpose")
def _conv2d_transpose(x, weight, bias, stride, padding, output_padding,
                      dilation, groups, channel_last):
    # paddle weight layout for transpose: [in, out/g, kh, kw]
    ndim = 2
    strides = _norm_tuple(stride, ndim)
    pads = _conv_padding(padding, ndim)
    if isinstance(pads, str):
        pads = [(0, 0)] * ndim if pads == "VALID" else None
    kh, kw = weight.shape[2], weight.shape[3]
    dil = _norm_tuple(dilation, ndim)
    opad = _norm_tuple(output_padding, ndim)
    # Use lax.conv_transpose with IOHW spec.
    dn = ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "IOHW", "NCHW")
    if groups > 1:
        # grouped transpose: split channels
        xs = jnp.split(x, groups, axis=-1 if channel_last else 1)
        ws = jnp.split(weight, groups, axis=0)
        outs = [lax.conv_transpose(xi, wi, strides=strides,
                                   padding=pads if pads is not None else "SAME",
                                   rhs_dilation=dil, dimension_numbers=dn,
                                   transpose_kernel=True)
                for xi, wi in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
    else:
        if pads is None:
            out = lax.conv_transpose(x, weight, strides=strides, padding="SAME",
                                     rhs_dilation=dil, dimension_numbers=dn,
                                     transpose_kernel=True)
        else:
            # effective padding for transpose: k-1-p
            eff = [(dil[i] * ((kh, kw)[i] - 1) - pads[i][0] ,
                    dil[i] * ((kh, kw)[i] - 1) - pads[i][1] + opad[i])
                   for i in range(ndim)]
            out = lax.conv_general_dilated(
                x, jnp.flip(weight, (2, 3)).swapaxes(0, 1),
                window_strides=(1, 1), padding=eff,
                lhs_dilation=strides, rhs_dilation=dil,
                dimension_numbers=lax.conv_dimension_numbers(
                    x.shape, weight.shape[1::-1] + weight.shape[2:],
                    ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")))
    if bias is not None:
        shape = [1] * out.ndim
        shape[out.ndim - 1 if channel_last else 1] = bias.shape[0]
        out = out + bias.reshape(shape)
    return out


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCHW", output_size=None, name=None):
    channel_last = data_format == "NHWC"
    if output_size is not None:
        from .pool_conv import opad_from_output_size
        in_sp = tuple(x.shape[1:3]) if channel_last else tuple(x.shape[2:4])
        output_padding = opad_from_output_size(
            output_size, in_sp, stride, padding, dilation,
            tuple(weight.shape[2:]), 2)
    return _conv2d_transpose(x, weight, bias, stride, padding, output_padding,
                             dilation, groups, channel_last)


# ----------------------------------------------------------------- pooling
def _pool(x, ksize, stride, padding, reducer, init, ndim, channel_last,
          ceil_mode=False, count_include_pad=True, is_avg=False):
    ks = _norm_tuple(ksize, ndim)
    st = _norm_tuple(stride if stride is not None else ksize, ndim)
    if channel_last:
        window = (1,) + ks + (1,)
        strides = (1,) + st + (1,)
        spatial = list(range(1, 1 + ndim))
    else:
        window = (1, 1) + ks
        strides = (1, 1) + st
        spatial = list(range(2, 2 + ndim))
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _conv_padding(padding, ndim)
        full = [(0, 0)] * x.ndim
        for i, d in enumerate(spatial):
            full[d] = p[i]
        pad = full
    if is_avg:
        summed = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
        if count_include_pad or pad == "VALID":
            denom = np.prod(ks)
            return summed / denom
        ones = jnp.ones_like(x)
        counts = lax.reduce_window(ones, 0.0, lax.add, window, strides, pad)
        return summed / counts
    return lax.reduce_window(x, init, reducer, window, strides, pad)


@def_op("max_pool2d")
def _max_pool2d(x, ksize, stride, padding, channel_last, ceil_mode):
    return _pool(x, ksize, stride, padding, lax.max, -jnp.inf, 2, channel_last,
                 ceil_mode)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        from .extra import max_pool2d_with_index
        from ...tensor.manipulation import transpose
        if data_format == "NHWC":
            pooled, idx = max_pool2d_with_index(
                transpose(x, [0, 3, 1, 2]), kernel_size, stride, padding,
                ceil_mode)
            return transpose(pooled, [0, 2, 3, 1]), \
                transpose(idx, [0, 2, 3, 1])
        return max_pool2d_with_index(x, kernel_size, stride, padding,
                                     ceil_mode)
    return _max_pool2d(x, kernel_size, stride, padding, data_format == "NHWC",
                       ceil_mode)


@def_op("avg_pool2d")
def _avg_pool2d(x, ksize, stride, padding, channel_last, ceil_mode, cip):
    return _pool(x, ksize, stride, padding, None, None, 2, channel_last,
                 ceil_mode, cip, is_avg=True)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW",
               name=None):
    return _avg_pool2d(x, kernel_size, stride, padding, data_format == "NHWC",
                       ceil_mode, not exclusive)


@def_op("max_pool1d")
def _max_pool1d(x, ksize, stride, padding, channel_last, ceil_mode):
    return _pool(x, ksize, stride, padding, lax.max, -jnp.inf, 1, channel_last,
                 ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, name=None):
    if return_mask:
        # singleton-W plane: the flat plane argmax IS the L index
        from .extra import max_pool2d_with_index
        k = _norm_tuple(kernel_size, 1)[0]
        s = _norm_tuple(stride if stride is not None else kernel_size, 1)[0]
        p = _norm_tuple(padding, 1)[0]
        pooled, idx = max_pool2d_with_index(
            x[..., None], (k, 1), (s, 1), (p, 0), ceil_mode)
        return pooled[..., 0], idx[..., 0]
    return _max_pool1d(x, kernel_size, stride, padding, False, ceil_mode)


@def_op("avg_pool1d")
def _avg_pool1d(x, ksize, stride, padding, channel_last, ceil_mode, cip):
    return _pool(x, ksize, stride, padding, None, None, 1, channel_last,
                 ceil_mode, cip, is_avg=True)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, name=None):
    return _avg_pool1d(x, kernel_size, stride, padding, False, ceil_mode,
                       not exclusive)


@def_op("adaptive_avg_pool2d_")
def _adaptive_avg_pool2d(x, out_hw, channel_last):
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    oh, ow = out_hw
    # split into oh x ow regions (paddle adaptive pooling semantics)
    def pool_axis(arr, axis, out_size):
        in_size = arr.shape[axis]
        if in_size % out_size == 0:
            k = in_size // out_size
            shape = list(arr.shape)
            shape[axis] = out_size
            shape.insert(axis + 1, k)
            return jnp.mean(arr.reshape(shape), axis=axis + 1)
        # general: average via interval sums
        starts = (np.arange(out_size) * in_size) // out_size
        ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
        segs = [jnp.mean(lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                         axis=axis, keepdims=True) for s, e in zip(starts, ends)]
        return jnp.concatenate(segs, axis=axis)
    out = pool_axis(x, 2, oh)
    out = pool_axis(out, 3, ow)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    hw = _norm_tuple(output_size, 2)
    return _adaptive_avg_pool2d(x, hw, data_format == "NHWC")


def adaptive_avg_pool1d(x, output_size, name=None):
    out = _adaptive_avg_pool2d(x[..., None], (_norm_tuple(output_size, 1)[0], 1),
                               False)
    return out[..., 0]


@def_op("adaptive_max_pool2d_")
def _adaptive_max_pool2d(x, out_hw):
    def pool_axis(arr, axis, out_size):
        in_size = arr.shape[axis]
        starts = (np.arange(out_size) * in_size) // out_size
        ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
        segs = [jnp.max(lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                        axis=axis, keepdims=True) for s, e in zip(starts, ends)]
        return jnp.concatenate(segs, axis=axis)
    out = pool_axis(x, 2, out_hw[0])
    return pool_axis(out, 3, out_hw[1])


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    return _adaptive_max_pool2d(x, _norm_tuple(output_size, 2))


# ------------------------------------------------------------------- norms
@def_op("batch_norm_f")
def _batch_norm(x, mean, variance, weight, bias, epsilon, channel_last):
    shape = [1] * x.ndim
    shape[x.ndim - 1 if channel_last else 1] = x.shape[x.ndim - 1 if channel_last else 1]
    inv = lax.rsqrt(variance.reshape(shape) + epsilon)
    out = (x - mean.reshape(shape)) * inv
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """reference: nn/functional/norm.py batch_norm.

    In training mode, batch statistics are used and running stats are updated
    in-place on the provided tensors (eager semantics).
    """
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    ch_dim = x.ndim - 1 if channel_last else 1
    if training and not use_global_stats:
        axes = tuple(i for i in range(x.ndim) if i != ch_dim)
        from ... import tensor as T
        batch_mean = T.mean(x, axis=list(axes))
        batch_var = T.var(x, axis=list(axes), unbiased=False)
        out = _batch_norm(x, batch_mean, batch_var, weight, bias, epsilon,
                          channel_last)
        if running_mean is not None:
            n = np.prod([x.shape[i] for i in axes])
            unbiased = batch_var.detach() * (n / builtins.max(n - 1, 1))
            if not isinstance(batch_mean._data, jax.core.Tracer):
                running_mean._data = (momentum * running_mean._data
                                      + (1 - momentum) * batch_mean.detach()._data)
                running_var._data = (momentum * running_var._data
                                     + (1 - momentum) * unbiased._data)
        return out
    return _batch_norm(x, running_mean, running_var, weight, bias, epsilon,
                       channel_last)


@def_op("layer_norm_f")
def _layer_norm(x, weight, bias, epsilon, begin_axis):
    axes = tuple(range(begin_axis, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, (int, np.integer)):
        normalized_shape = [int(normalized_shape)]
    begin = x.ndim - len(normalized_shape)
    return _layer_norm(x, weight, bias, epsilon, begin)


@def_op("rms_norm_f")
def _rms_norm(x, weight, epsilon):
    """Fused rmsnorm: XLA fuses the chain by default; per shape,
    ops/autotune may pick the single-pass Pallas kernel
    (ops/pallas/fused_norm_rope.py, custom_vjp so training
    differentiates through it) on TPU."""
    from ...ops import autotune as _autotune
    from ...ops.pallas.fused_norm_rope import rms_norm_fused, rms_norm_xla

    if weight is not None and x.ndim >= 2 \
            and weight.shape == x.shape[-1:]:
        key = f"rms_norm:{tuple(x.shape)}:{x.dtype}"
        impl = _autotune.select(
            key, x,
            {"xla": lambda: rms_norm_xla(x, weight, epsilon),
             "pallas": lambda: rms_norm_fused(x, weight, epsilon)},
            default="xla")
        if impl == "pallas":
            return rms_norm_fused(x, weight, epsilon)
    return rms_norm_xla(x, weight, epsilon)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    return _rms_norm(x, weight, epsilon)


@def_op("group_norm_f")
def _group_norm(x, weight, bias, groups, epsilon, channel_last):
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    n, c = x.shape[0], x.shape[1]
    g = groups
    xg = x.reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xg - mean), axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + epsilon)).reshape(x.shape)
    shape = [1] * x.ndim
    shape[1] = c
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    return _group_norm(x, weight, bias, num_groups, epsilon,
                       data_format == "NHWC")


@def_op("instance_norm_f")
def _instance_norm(x, weight, bias, epsilon):
    axes = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(x - mean), axis=axes, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + epsilon)
    shape = [1, x.shape[1]] + [1] * (x.ndim - 2)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    return _instance_norm(x, weight, bias, eps)


@def_op("local_response_norm")
def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0):
    sq = jnp.square(x)
    half = size // 2
    c = x.shape[1]
    pads = [(0, 0)] * x.ndim
    pads[1] = (half, size - half - 1)
    padded = jnp.pad(sq, pads)
    window = [1] * x.ndim
    window[1] = size
    summed = lax.reduce_window(padded, 0.0, lax.add, tuple(window),
                               (1,) * x.ndim, "VALID")
    return x / jnp.power(k + alpha * summed, beta)


# ------------------------------------------------------------------ losses
def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("cross_entropy_f")
def _cross_entropy(logits, label, weight, ignore_index, reduction, soft_label,
                   axis, label_smoothing):
    if soft_label:
        logp = jax.nn.log_softmax(logits, axis=axis)
        if label_smoothing > 0:
            k = logits.shape[axis]
            label = (1 - label_smoothing) * label + label_smoothing / k
        loss = -jnp.sum(label * logp, axis=axis)
        return _reduce(loss, reduction)
    logp = jax.nn.log_softmax(logits, axis=axis)
    lbl = label
    if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
        lbl = jnp.squeeze(lbl, axis=axis)
    valid = (lbl != ignore_index)
    safe = jnp.where(valid, lbl, 0)
    picked = jnp.take_along_axis(
        logp, jnp.expand_dims(safe, axis).astype(jnp.int32), axis=axis)
    loss = -jnp.squeeze(picked, axis=axis)
    if label_smoothing > 0:
        k = logits.shape[axis]
        smooth = -jnp.mean(logp, axis=axis)
        loss = (1 - label_smoothing) * loss + label_smoothing * smooth
    if weight is not None:
        w = jnp.take(weight, safe)
        loss = loss * w
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0) \
            if weight is None else jnp.sum(jnp.where(valid, jnp.take(weight, safe), 0.0))
        return jnp.sum(loss) / denom
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    """reference: python/paddle/nn/functional/loss.py cross_entropy."""
    if not use_softmax:
        return nll_loss(call_op("log", lambda x: jnp.log(x), (input,), {}),
                        label, weight, ignore_index, reduction)
    return _cross_entropy(input, label, weight, ignore_index, reduction,
                          soft_label, axis, label_smoothing)


@def_op("nll_loss_f")
def _nll_loss(logp, label, weight, ignore_index, reduction):
    valid = (label != ignore_index)
    safe = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(logp, safe[:, None].astype(jnp.int32), axis=1)
    loss = -picked[:, 0]
    if weight is not None:
        loss = loss * jnp.take(weight, safe)
    loss = jnp.where(valid, loss, 0.0)
    if reduction == "mean":
        denom = jnp.sum(valid.astype(loss.dtype)) if weight is None else \
            jnp.sum(jnp.where(valid, jnp.take(weight, safe), 0.0))
        return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
    return _reduce(loss, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    return _nll_loss(input, label, weight, ignore_index, reduction)


@def_op("mse_loss_f")
def _mse(input, label, reduction):
    return _reduce(jnp.square(input - label), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    return _mse(input, label, reduction)


@def_op("l1_loss_f")
def _l1(input, label, reduction):
    return _reduce(jnp.abs(input - label), reduction)


def l1_loss(input, label, reduction="mean", name=None):
    return _l1(input, label, reduction)


@def_op("smooth_l1_f")
def _smooth_l1(input, label, reduction, delta):
    diff = jnp.abs(input - label)
    loss = jnp.where(diff < delta, 0.5 * diff * diff / delta,
                     diff - 0.5 * delta)
    return _reduce(loss, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    return _smooth_l1(input, label, reduction, delta)


@def_op("huber_loss")
def huber_loss(input, label, delta=1.0, reduction="mean"):
    diff = jnp.abs(input - label)
    return _reduce(jnp.where(diff <= delta, 0.5 * diff * diff,
                             delta * (diff - 0.5 * delta)), reduction)


@def_op("bce_f")
def _bce(input, label, weight, reduction):
    eps = 1e-12
    loss = -(label * jnp.log(jnp.maximum(input, eps))
             + (1 - label) * jnp.log(jnp.maximum(1 - input, eps)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean",
                         name=None):
    return _bce(input, label, weight, reduction)


@def_op("bce_logits_f")
def _bce_logits(logit, label, weight, pos_weight, reduction):
    max_val = jnp.maximum(-logit, 0.0)
    if pos_weight is not None:
        log_w = (pos_weight - 1.0) * label + 1.0
        loss = (1 - label) * logit + log_w * (
            jnp.log1p(jnp.exp(-jnp.abs(logit))) + max_val)
    else:
        loss = (1 - label) * logit + max_val + jnp.log1p(
            jnp.exp(-jnp.abs(logit)))
    if weight is not None:
        loss = loss * weight
    return _reduce(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    return _bce_logits(logit, label, weight, pos_weight, reduction)


@def_op("kl_div_f")
def _kl_div(input, label, reduction, log_target):
    if log_target:
        loss = jnp.exp(label) * (label - input)
    else:
        loss = jnp.where(label > 0, label * (jnp.log(jnp.maximum(label, 1e-12))
                                             - input), 0.0)
    if reduction == "batchmean":
        return jnp.sum(loss) / input.shape[0]
    return _reduce(loss, reduction)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    return _kl_div(input, label, reduction, log_target)


@def_op("margin_ranking_f")
def _margin_ranking(x1, x2, label, margin, reduction):
    return _reduce(jnp.maximum(0.0, -label * (x1 - x2) + margin), reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    return _margin_ranking(input, other, label, margin, reduction)


@def_op("hinge_embedding_f")
def _hinge_embedding(input, label, margin, reduction):
    loss = jnp.where(label == 1.0, input,
                     jnp.maximum(0.0, margin - input))
    return _reduce(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    return _hinge_embedding(input, label, margin, reduction)


@def_op("cosine_embedding_f")
def _cosine_embedding(x1, x2, label, margin, reduction):
    cos = jnp.sum(x1 * x2, axis=-1) / jnp.maximum(
        jnp.linalg.norm(x1, axis=-1) * jnp.linalg.norm(x2, axis=-1), 1e-12)
    loss = jnp.where(label == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce(loss, reduction)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    return _cosine_embedding(input1, input2, label, margin, reduction)


@def_op("triplet_margin_f")
def _triplet(anchor, positive, negative, margin, p, eps, swap, reduction):
    dp = jnp.power(jnp.sum(jnp.power(jnp.abs(anchor - positive) + eps, p), -1),
                   1.0 / p)
    dn = jnp.power(jnp.sum(jnp.power(jnp.abs(anchor - negative) + eps, p), -1),
                   1.0 / p)
    if swap:
        dpn = jnp.power(jnp.sum(jnp.power(jnp.abs(positive - negative) + eps, p),
                                -1), 1.0 / p)
        dn = jnp.minimum(dn, dpn)
    return _reduce(jnp.maximum(0.0, dp - dn + margin), reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    return _triplet(input, positive, negative, margin, p, epsilon, swap,
                    reduction)


@def_op("square_error_cost")
def square_error_cost(input, label):
    return jnp.square(input - label)


@def_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy(logits, label, soft_label=False,
                               ignore_index=-100, axis=-1,
                               return_softmax=False):
    sm = jax.nn.softmax(logits, axis=axis)
    logp = jax.nn.log_softmax(logits, axis=axis)
    if soft_label:
        loss = -jnp.sum(label * logp, axis=axis, keepdims=True)
    else:
        lbl = label
        if lbl.ndim == logits.ndim and lbl.shape[axis] == 1:
            lbl = jnp.squeeze(lbl, axis=axis)
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lbl, axis).astype(jnp.int32), axis=axis)
        loss = -picked
    if return_softmax:
        return loss, sm
    return loss


# ----------------------------------------------------------- miscellaneous
@def_op("interpolate_")
def _interpolate(x, out_hw, mode, align_corners, channel_last):
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    n, c, h, w = x.shape
    oh, ow = out_hw
    if mode == "nearest":
        ridx = (jnp.arange(oh) * h // oh).astype(jnp.int32)
        cidx = (jnp.arange(ow) * w // ow).astype(jnp.int32)
        out = x[:, :, ridx][:, :, :, cidx]
    else:  # bilinear
        if align_corners and oh > 1 and ow > 1:
            ys = jnp.linspace(0, h - 1, oh)
            xs = jnp.linspace(0, w - 1, ow)
        else:
            ys = (jnp.arange(oh) + 0.5) * h / oh - 0.5
            xs = (jnp.arange(ow) + 0.5) * w / ow - 0.5
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1).astype(jnp.int32)
        y1 = jnp.clip(y0 + 1, 0, h - 1)
        x1 = jnp.clip(x0 + 1, 0, w - 1)
        wy = jnp.clip(ys - y0, 0, 1)[None, None, :, None]
        wx = jnp.clip(xs - x0, 0, 1)[None, None, None, :]
        v00 = x[:, :, y0][:, :, :, x0]
        v01 = x[:, :, y0][:, :, :, x1]
        v10 = x[:, :, y1][:, :, :, x0]
        v11 = x[:, :, y1][:, :, :, x1]
        out = (v00 * (1 - wy) * (1 - wx) + v01 * (1 - wy) * wx
               + v10 * wy * (1 - wx) + v11 * wy * wx).astype(x.dtype)
    if channel_last:
        out = jnp.moveaxis(out, 1, -1)
    return out


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    channel_last = data_format == "NHWC"
    h_dim = 1 if channel_last else 2
    if size is None:
        sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
            else [scale_factor, scale_factor]
        size = [int(x.shape[h_dim] * sf[0]), int(x.shape[h_dim + 1] * sf[1])]
    size = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in size]
    return _interpolate(x, tuple(size), mode, align_corners, channel_last)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


@def_op("pixel_shuffle")
def pixel_shuffle(x, upscale_factor, data_format="NCHW"):
    r = upscale_factor
    if data_format == "NCHW":
        n, c, h, w = x.shape
        out = x.reshape(n, c // (r * r), r, r, h, w)
        out = out.transpose(0, 1, 4, 2, 5, 3)
        return out.reshape(n, c // (r * r), h * r, w * r)
    n, h, w, c = x.shape
    out = x.reshape(n, h, w, r, r, c // (r * r))
    out = out.transpose(0, 1, 3, 2, 4, 5)
    return out.reshape(n, h * r, w * r, c // (r * r))


@def_op("pixel_unshuffle")
def pixel_unshuffle(x, downscale_factor, data_format="NCHW"):
    r = downscale_factor
    n, c, h, w = x.shape
    out = x.reshape(n, c, h // r, r, w // r, r)
    out = out.transpose(0, 1, 3, 5, 2, 4)
    return out.reshape(n, c * r * r, h // r, w // r)


@def_op("unfold")
def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1):
    ks = _norm_tuple(kernel_sizes, 2)
    st = _norm_tuple(strides, 2)
    pd = _norm_tuple(paddings, 2)
    dl = _norm_tuple(dilations, 2)
    n, c, h, w = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1])))
    oh = (h + 2 * pd[0] - dl[0] * (ks[0] - 1) - 1) // st[0] + 1
    ow = (w + 2 * pd[1] - dl[1] * (ks[1] - 1) - 1) // st[1] + 1
    cols = []
    for i in range(ks[0]):
        for j in range(ks[1]):
            patch = xp[:, :, i * dl[0]: i * dl[0] + oh * st[0]: st[0],
                       j * dl[1]: j * dl[1] + ow * st[1]: st[1]]
            cols.append(patch.reshape(n, c, -1))
    return jnp.stack(cols, axis=2).reshape(n, c * ks[0] * ks[1], -1)


from .attention import (  # noqa: E402,F401
    scaled_dot_product_attention, flash_attention,
)
from ...tensor.manipulation import pad  # noqa: E402,F401


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW"):
    def _fn(x):
        nt, c, h, w = x.shape
        n = nt // seg_num
        xr = x.reshape(n, seg_num, c, h, w)
        fold = int(c * shift_ratio)
        left = jnp.concatenate([xr[:, 1:, :fold], jnp.zeros_like(xr[:, :1, :fold])], 1)
        right = jnp.concatenate([jnp.zeros_like(xr[:, :1, fold:2 * fold]),
                                 xr[:, :-1, fold:2 * fold]], 1)
        rest = xr[:, :, 2 * fold:]
        return jnp.concatenate([left, right, rest], axis=2).reshape(nt, c, h, w)
    return call_op("temporal_shift", _fn, (x,), {})


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    def _fn(lengths):
        ml = maxlen if maxlen is not None else int(jnp.max(lengths))
        return (jnp.arange(ml)[None, :] < lengths[:, None]).astype(
            dtypes.convert_dtype(dtype))
    return call_op("sequence_mask", _fn, (lengths,), {})


def feature_alpha_dropout(x, p=0.5, training=True, name=None):
    """reference: F.feature_alpha_dropout — alpha dropout zeroing whole
    channel maps (axis 1), preserving self-normalizing statistics."""
    if not training or p == 0:
        return x * 1.0
    alpha = -1.7580993408473766

    def _fn(x, key):
        shape = (x.shape[0], x.shape[1]) + (1,) * (x.ndim - 2)
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        a = ((1 - p) * (1 + p * alpha ** 2)) ** -0.5
        b = -a * alpha * p
        return (a * jnp.where(keep, x, alpha) + b).astype(x.dtype)
    return call_op("feature_alpha_dropout", _fn,
                   (x, _random.split_key()), {})


@def_op("zeropad2d")
def zeropad2d(x, padding, data_format="NCHW", name=None):
    l, r, t, b = padding
    if data_format == "NCHW":
        return jnp.pad(x, ((0, 0), (0, 0), (t, b), (l, r)))
    return jnp.pad(x, ((0, 0), (t, b), (l, r), (0, 0)))


# gather_tree: single registered implementation lives in tensor/extra_ops
# (re-registering here would shadow its OP_REGISTRY entry)
from ...tensor.extra_ops import gather_tree  # noqa: E402


# --------------------------------------------------------------- in-place
def _inplace(fn):
    """Paddle-style ``op_(x)``: run the out-of-place op, then move its
    value AND tape linkage onto x (the in-place result participates in
    autograd exactly like the out-of-place one)."""
    import functools

    @functools.wraps(fn)
    def inner(x, *args, **kwargs):
        y = fn(x, *args, **kwargs)
        x._data = y._data
        x.stop_gradient = y.stop_gradient
        x._grad_node = getattr(y, "_grad_node", None)
        x._node_out_idx = getattr(y, "_node_out_idx", 0)
        return x
    return inner


relu_ = _inplace(_g["relu"])
tanh_ = _inplace(_g["tanh"])
elu_ = _inplace(elu)
hardtanh_ = _inplace(hardtanh)
leaky_relu_ = _inplace(leaky_relu)
softmax_ = _inplace(softmax)


from .ctc import ctc_loss, ctc_decode  # noqa: E402,F401
from .extra import (  # noqa: E402,F401
    nearest_interp, bilinear_interp, bicubic_interp, linear_interp,
    trilinear_interp, affine_grid, grid_sample, fold,
    max_pool2d_with_index, max_unpool2d, lp_pool2d, channel_shuffle,
    tanh_shrink, thresholded_relu, swiglu, rrelu,
    sigmoid_cross_entropy_with_logits, hinge_loss, log_loss, identity_loss,
    hsigmoid_loss, margin_cross_entropy, class_center_sample,
    fused_softmax_mask, fused_softmax_mask_upper_triangle,
    pad3d, fractional_max_pool2d, affine_channel, shuffle_channel,
    bce_loss, kldiv_loss, logsigmoid, max_unpool3d, l2_normalize, ctc_align,
)
from . import extra  # noqa: E402,F401

log_sigmoid = logsigmoid
thresholded_relu_ = _inplace(thresholded_relu)

from .pool_conv import (  # noqa: E402,F401
    max_pool3d, max_pool3d_with_index, avg_pool3d, adaptive_avg_pool3d,
    adaptive_max_pool1d, adaptive_max_pool3d, lp_pool1d,
    fractional_max_pool3d, max_unpool1d, conv1d_transpose, conv3d_transpose,
)
from .attention import (  # noqa: E402,F401
    flash_attn_qkvpacked, flash_attn_varlen_qkvpacked, flashmask_attention,
    sparse_attention,
)
from .loss_extra import (  # noqa: E402,F401
    gaussian_nll_loss, poisson_nll_loss, soft_margin_loss,
    multi_label_soft_margin_loss, multi_margin_loss,
    triplet_margin_with_distance_loss, pairwise_distance, dice_loss,
    npair_loss, sigmoid_focal_loss, rnnt_loss,
    adaptive_log_softmax_with_loss,
)
