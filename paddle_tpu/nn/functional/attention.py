"""Attention functional API.

Capability parity: python/paddle/nn/functional/flash_attention.py:364
(flash_attention, scaled_dot_product_attention) in the reference.

Implementation selection (SURVEY #86 kernel autotune): at short sequence /
small head_dim the plain XLA fusion beats the Pallas online-softmax kernel
on TPU (measured: v5e, d=64, s=1024 — the s x s score matrix still fits and
XLA's fusion pipeline wins); at long sequence its O(s^2) f32 residuals OOM
and the Pallas kernel is the only viable path.  Eager calls autotune per
shape (cached); traced calls use the cache or the memory heuristic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.dispatch import def_op
from ...ops import autotune as _autotune
from ...ops.pallas.flash_attention import flash_attention_bshd, mha_reference

# per-call f32 score-matrix bytes above which the XLA path is assumed to
# OOM/thrash during training (backward keeps one s x s residual per layer)
_XLA_SCORE_BYTES_LIMIT = 1 << 29


def _mha_ref_bshd(q, k, v, causal):
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    return jnp.swapaxes(mha_reference(qt, kt, vt, causal=causal), 1, 2)


def _choose_flash_impl(q, k, causal) -> str:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    score_bytes = b * h * sq * sk * 4
    heuristic = "xla" if score_bytes <= _XLA_SCORE_BYTES_LIMIT else "pallas"
    key = (f"flash_attention:{tuple(q.shape)}:{tuple(k.shape)}:"
           f"{q.dtype}:{causal}")
    if isinstance(q, jax.core.Tracer):
        return _autotune.lookup(key) or heuristic
    if heuristic == "pallas":
        # don't risk OOM timing the XLA candidate on huge scores
        return "pallas"
    return _autotune.autotune(
        key,
        {"xla": lambda: _mha_ref_bshd(q, k, k, causal),
         "pallas": lambda: flash_attention_bshd(q, k, k, causal=causal)},
        default=heuristic)


def _flash_impl(q, k, v, causal):
    if _choose_flash_impl(q, k, causal) == "xla":
        return _mha_ref_bshd(q, k, v, causal)
    return flash_attention_bshd(q, k, v, causal=causal)


@def_op("flash_attention")
def _flash(q, k, v, causal):
    return _flash_impl(q, k, v, causal)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference API: paddle.nn.functional.flash_attention.flash_attention.

    Layout (batch, seq, num_heads, head_dim).  Dropout inside attention is
    not fused (XLA/Pallas path); apply dropout on the output if needed.
    """
    out = _flash(query, key, value, causal)
    if return_softmax:
        return out, None
    return out, None


@def_op("sdpa")
def _sdpa(q, k, v, attn_mask, causal, dropout_p):
    if attn_mask is None:
        return _flash_impl(q, k, v, causal)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = mha_reference(qt, kt, vt, causal=causal, bias=attn_mask)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """reference: paddle.nn.functional.scaled_dot_product_attention
    (flash_attention.py).  Layout (batch, seq, heads, head_dim)."""
    return _sdpa(query, key, value, attn_mask, is_causal, dropout_p)
