"""Attention functional API.

Capability parity: python/paddle/nn/functional/flash_attention.py:364
(flash_attention, scaled_dot_product_attention) in the reference.

Implementation selection (SURVEY #86 kernel autotune): at short sequence /
small head_dim the plain XLA fusion beats the Pallas online-softmax kernel
on TPU (measured: v5e, d=64, s=1024 — the s x s score matrix still fits and
XLA's fusion pipeline wins); at long sequence its O(s^2) f32 residuals OOM
and the Pallas kernel is the only viable path.  Eager calls autotune per
shape (cached); traced calls use the cache or the memory heuristic.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...framework.dispatch import def_op
from ...ops import autotune as _autotune
from ...ops.pallas.flash_attention import flash_attention_bshd, mha_reference

# per-call f32 score-matrix bytes above which the XLA path is assumed to
# OOM/thrash during training (backward keeps one s x s residual per layer)
_XLA_SCORE_BYTES_LIMIT = 1 << 29


def _flashmask_pallas_module():
    """The Pallas flashmask module when it should handle dispatch, else
    None.  _FORCE_DISPATCH (tests) is separate from _INTERPRET so the
    dense path below stays reachable as the correctness ORACLE while the
    kernels run interpreted."""
    from ...ops.pallas import flashmask_attention as _fm
    if jax.default_backend() == "tpu" or getattr(_fm, "_FORCE_DISPATCH",
                                                 False):
        return _fm
    return None


def _mha_ref_bshd(q, k, v, causal):
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    return jnp.swapaxes(mha_reference(qt, kt, vt, causal=causal), 1, 2)


def _choose_flash_impl(q, k, causal) -> str:
    b, sq, h, d = q.shape
    sk = k.shape[1]
    score_bytes = b * h * sq * sk * 4
    heuristic = "xla" if score_bytes <= _XLA_SCORE_BYTES_LIMIT else "pallas"
    key = (f"flash_attention:{tuple(q.shape)}:{tuple(k.shape)}:"
           f"{q.dtype}:{causal}")
    if isinstance(q, jax.core.Tracer):
        return _autotune.lookup(key) or heuristic
    if heuristic == "pallas":
        # don't risk OOM timing the XLA candidate on huge scores
        return "pallas"
    return _autotune.autotune(
        key,
        {"xla": lambda: _mha_ref_bshd(q, k, k, causal),
         "pallas": lambda: flash_attention_bshd(q, k, k, causal=causal)},
        default=heuristic)


def _flash_impl(q, k, v, causal):
    if _choose_flash_impl(q, k, causal) == "xla":
        return _mha_ref_bshd(q, k, v, causal)
    return flash_attention_bshd(q, k, v, causal=causal)


@def_op("flash_attention")
def _flash(q, k, v, causal):
    return _flash_impl(q, k, v, causal)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference API: paddle.nn.functional.flash_attention.flash_attention.

    Layout (batch, seq, num_heads, head_dim).  Dropout inside attention is
    not fused (XLA/Pallas path); apply dropout on the output if needed.
    """
    out = _flash(query, key, value, causal)
    if return_softmax:
        return out, None
    return out, None


@def_op("sdpa")
def _sdpa(q, k, v, attn_mask, causal, dropout_p):
    if attn_mask is None:
        return _flash_impl(q, k, v, causal)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = mha_reference(qt, kt, vt, causal=causal, bias=attn_mask)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """reference: paddle.nn.functional.scaled_dot_product_attention
    (flash_attention.py).  Layout (batch, seq, heads, head_dim)."""
    return _sdpa(query, key, value, attn_mask, is_causal, dropout_p)


@def_op("flash_attn_qkvpacked")
def _flash_qkvpacked(qkv, causal):
    # [B, S, 3, H, D] -> three [B, S, H, D]
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    return _flash_impl(q, k, v, causal)


def flash_attn_qkvpacked(qkv, dropout=0.0, causal=False,
                         return_softmax=False, fixed_seed_offset=None,
                         rng_name="", training=True, name=None):
    """reference: F.flash_attn_qkvpacked (flash_attention.py) — packed
    [batch, seq, 3, heads, head_dim] input."""
    out = _flash_qkvpacked(qkv, causal)
    return out, None


def _varlen_segment_mask(cu_seqlens, total, dtype):
    """Segment ids from cumulative sequence lengths: position i belongs to
    the sequence whose [cu[j], cu[j+1]) interval contains it."""
    pos = jnp.arange(total)
    seg = jnp.searchsorted(cu_seqlens[1:-1], pos, side="right") \
        if cu_seqlens.shape[0] > 2 else jnp.zeros((total,), jnp.int32)
    return seg


@def_op("flash_attn_varlen_qkvpacked")
def _flash_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, causal, scale):
    # qkv: [total, 3, H, D] — ragged batch packed along axis 0.  On TPU the
    # ragged batch runs as ONE attention with a block-diagonal segment mask
    # (the reference's varlen kernel iterates cu_seqlens on the GPU side).
    total = qkv.shape[0]
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]
    seg_q = _varlen_segment_mask(cu_seqlens_q, total, q.dtype)
    seg_k = _varlen_segment_mask(cu_seqlens_k, k.shape[0], k.dtype)
    mask = (seg_q[:, None] == seg_k[None, :])
    if causal:
        mask = mask & (jnp.arange(total)[:, None] >= jnp.arange(
            k.shape[0])[None, :])
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [total, H, D] -> heads-leading matmul
    qt = jnp.swapaxes(q, 0, 1) * s                  # [H, total, D]
    kt = jnp.swapaxes(k, 0, 1)
    vt = jnp.swapaxes(v, 0, 1)
    scores = qt @ jnp.swapaxes(kt, -1, -2) + bias[None]
    probs = jax.nn.softmax(scores, axis=-1)
    out = probs @ vt                                # [H, total, D]
    return jnp.swapaxes(out, 0, 1)


def flash_attn_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k,
                                max_seqlen_q=None, max_seqlen_k=None,
                                scale=None, dropout=0.0, causal=False,
                                return_softmax=False, varlen_padded=False,
                                training=True, name=None):
    """reference: F.flash_attn_varlen_qkvpacked — ragged sequences packed
    as [total_tokens, 3, heads, head_dim] with cu_seqlens boundaries."""
    out = _flash_varlen_qkvpacked(qkv, cu_seqlens_q, cu_seqlens_k, causal,
                                  scale)
    return out, None


@def_op("flashmask_attention")
def _flashmask_attention(q, k, v, startend_row_indices, causal):
    # startend_row_indices: [B, H or 1, Sk, 1|2|4] — FlashMask (the
    # reference's flashmask_attention): column j of the score matrix is
    # masked for rows r in [start_j, end_j).  1 col: causal LT mask with
    # rows >= start masked; 2 cols: [start, end); 4 cols: LT + UT bands.
    # On TPU the Pallas interval-mask kernels run (O(seq) mask memory +
    # fully-masked tiles skipped — ops/pallas/flashmask_attention.py);
    # _flashmask_dense below is the CPU fallback and oracle.
    _fm = _flashmask_pallas_module()
    if _fm is not None:
        qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
        out = _fm.flashmask_attention_fused(qt, kt, vt,
                                            startend_row_indices, causal)
        return jnp.swapaxes(out, 1, 2)
    return _flashmask_dense(q, k, v, startend_row_indices, causal)


def _flashmask_dense(q, k, v, startend_row_indices, causal):
    """Dense-bias FlashMask (CPU fallback + the kernels' oracle)."""
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    idx = startend_row_indices
    rows = jnp.arange(Sq)[:, None]                  # [Sq, 1]

    def band(lo, hi):
        # mask rows lo <= r < hi, per column: [B, h, Sq, Sk]
        return (rows[None, None] >= lo[:, :, None, :]) & \
               (rows[None, None] < hi[:, :, None, :])

    ncol = idx.shape[-1]
    if ncol == 1:
        masked = band(idx[..., 0], jnp.full_like(idx[..., 0], Sq))
    elif ncol == 2:
        masked = band(idx[..., 0], idx[..., 1])
    else:                                           # 4: LT start/end + UT
        masked = band(idx[..., 0], idx[..., 1]) | \
                 band(idx[..., 2], idx[..., 3])
    if causal:
        masked = masked | (rows[None, None] < jnp.arange(Sk)[None, None,
                                                            None, :])
    bias = jnp.where(masked, -1e30, 0.0).astype(jnp.float32)
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    out = mha_reference(qt, kt, vt, causal=False, bias=bias)
    return jnp.swapaxes(out, 1, 2)


def flashmask_attention(query, key, value, startend_row_indices,
                        dropout=0.0, causal=False, window_size=None,
                        return_softmax_lse=False, return_seed_offset=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """reference: F.flashmask_attention — sparse attention masks encoded
    as per-column row intervals (FlashMask, PaddlePaddle 3.0)."""
    out = _flashmask_attention(query, key, value, startend_row_indices,
                               causal)
    if return_softmax_lse or return_seed_offset:
        return (out, None) + ((None,) if return_seed_offset else ())
    return out


@def_op("sparse_attention")
def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """reference: F.sparse_attention — per-row CSR sparsity pattern over
    the score matrix.  [B, H, S, D] layout (reference layout).  On TPU the
    pattern is applied as a dense additive bias — XLA fuses it into the
    softmax; true block-sparse compute belongs to the Pallas kernel when
    the pattern is block-structured."""
    B, H, S, D = query.shape
    # dense mask[b, h, r, c] = 1 iff c in columns[offset[r]:offset[r+1]]
    nnz = sparse_csr_columns.shape[-1]
    pos = jnp.arange(nnz)

    def one_mask(offset, columns):
        row_of_nnz = jnp.searchsorted(offset[1:], pos, side="right")
        return jnp.zeros((S, S), bool).at[row_of_nnz, columns].set(True)

    mask = jax.vmap(one_mask)(
        sparse_csr_offset.reshape(B * H, -1),
        sparse_csr_columns.reshape(B * H, -1)).reshape(B, H, S, S)
    bias = jnp.where(mask, 0.0, -1e30).astype(jnp.float32)
    if attn_mask is not None:
        bias = bias + jnp.where(attn_mask.astype(bool), 0.0, -1e30)
    if key_padding_mask is not None:
        bias = bias + jnp.where(key_padding_mask.astype(bool), 0.0,
                                -1e30)[:, None, None, :]
    return mha_reference(query, key, value, causal=False, bias=bias)
