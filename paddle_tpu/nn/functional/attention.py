"""Attention functional API.

Capability parity: python/paddle/nn/functional/flash_attention.py:364
(flash_attention, scaled_dot_product_attention) in the reference.
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ...framework.dispatch import def_op
from ...ops.pallas.flash_attention import (
    flash_attention_bshd, flash_attention_bhsd, mha_reference,
)


@def_op("flash_attention")
def _flash(q, k, v, causal):
    return flash_attention_bshd(q, k, v, causal=causal)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference API: paddle.nn.functional.flash_attention.flash_attention.

    Layout (batch, seq, num_heads, head_dim).  Dropout inside attention is
    not fused (XLA/Pallas path); apply dropout on the output if needed.
    """
    out = _flash(query, key, value, causal)
    if return_softmax:
        return out, None
    return out, None


@def_op("sdpa")
def _sdpa(q, k, v, attn_mask, causal, dropout_p):
    # (b, s, h, d) -> (b, h, s, d)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if attn_mask is None:
        out = flash_attention_bhsd(qt, kt, vt, causal)
    else:
        out = mha_reference(qt, kt, vt, causal=causal, bias=attn_mask)
    return jnp.swapaxes(out, 1, 2)


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    """reference: paddle.nn.functional.scaled_dot_product_attention
    (flash_attention.py).  Layout (batch, seq, heads, head_dim)."""
    return _sdpa(query, key, value, attn_mask, is_causal, dropout_p)
