"""CTC loss + greedy decode.

Capability parity: python/paddle/nn/functional/loss.py ctc_loss:1907
(warpctc-backed in the reference: paddle/phi/kernels/impl/warpctc_kernel_impl.h)
and the legacy ctc_greedy_decoder.

TPU-native design: the forward-backward alpha recursion is a ``lax.scan``
over time in log space — one compiled loop with static shapes (labels padded
to max length, per-sample lengths masked), fully differentiable by jax
autodiff (no hand-written backward, unlike warpctc).  The extended label
sequence (blank-interleaved, 2L+1) is built with gathers so the whole loss
jits and batches."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import def_op
from ...framework.tensor import Tensor, wrap_array

_NEG_INF = -1e30   # finite sentinel: with finite operands jnp.logaddexp is
                   # NaN-free in both forward and backward (true -inf would
                   # produce inf-inf in its own grad; and tiny epsilons are
                   # subnormals XLA:CPU flushes to 0 -> log(0) NaNs)


def _log_add(a, b):
    return jnp.logaddexp(a, b)


@def_op("ctc_loss_")
def _ctc_loss(logits, labels, input_lengths, label_lengths, blank,
              norm_by_times):
    """logits [T, B, C]; labels [B, L] padded; per-sample NLL [B]."""
    T, B, C = logits.shape
    L = labels.shape[1]
    S = 2 * L + 1
    log_probs = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)

    lab = labels.astype(jnp.int32)
    # extended sequence: blank, l1, blank, l2, ..., blank  (length 2L+1)
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab)
    # transitions: s-2 allowed when ext[s] != blank and ext[s] != ext[s-2]
    can_skip = jnp.zeros((B, S), bool)
    can_skip = can_skip.at[:, 2:].set(
        (ext[:, 2:] != blank) & (ext[:, 2:] != ext[:, :-2]))

    ilen = input_lengths.astype(jnp.int32)
    llen = label_lengths.astype(jnp.int32)
    s_len = 2 * llen + 1                       # valid extended length

    # alpha_0
    init = jnp.full((B, S), _NEG_INF)
    p0 = log_probs[0]                          # [B, C]
    init = init.at[:, 0].set(p0[:, blank])
    init = init.at[:, 1].set(jnp.where(
        llen > 0, jnp.take_along_axis(p0, lab[:, 0:1], 1)[:, 0], _NEG_INF))

    def step(alpha, t):
        p = log_probs[t]                       # [B, C]
        emit = jnp.take_along_axis(p, ext, axis=1)      # [B, S]
        a_prev = alpha
        a_shift1 = jnp.concatenate(
            [jnp.full((B, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        a_shift2 = jnp.concatenate(
            [jnp.full((B, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        a = _log_add(a_prev, a_shift1)
        a = jnp.where(can_skip, _log_add(a, a_shift2), a)
        new_alpha = a + emit
        # frozen past the sample's input length (loss read at t = ilen-1)
        new_alpha = jnp.where((t < ilen)[:, None], new_alpha, alpha)
        return new_alpha, None

    alpha, _ = jax.lax.scan(step, init, jnp.arange(1, T))

    idx_last = jnp.clip(s_len - 1, 0, S - 1)
    idx_prev = jnp.clip(s_len - 2, 0, S - 1)
    a_last = jnp.take_along_axis(alpha, idx_last[:, None], 1)[:, 0]
    a_prev = jnp.take_along_axis(alpha, idx_prev[:, None], 1)[:, 0]
    a_prev = jnp.where(s_len >= 2, a_prev, _NEG_INF)
    nll = -_log_add(a_last, a_prev)
    if norm_by_times:
        nll = nll / jnp.maximum(ilen.astype(jnp.float32), 1.0)
    return nll


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """reference: paddle.nn.functional.ctc_loss (loss.py:1907) — takes raw
    LOGITS [max_logit_length, batch, num_classes+1] (softmax is integrated,
    matching warpctc), int labels [batch, max_label_length]."""
    nll = _ctc_loss(log_probs, labels, input_lengths, label_lengths,
                    int(blank), bool(norm_by_times))
    if reduction == "mean":
        ll = label_lengths
        denom = ll.astype("float32") if isinstance(ll, Tensor) else \
            wrap_array(jnp.asarray(np.asarray(ll), jnp.float32))
        return (nll / denom.clip(1.0)).mean()
    if reduction == "sum":
        return nll.sum()
    return nll


def ctc_decode(log_probs, input_lengths=None, blank=0):
    """Greedy (best-path) CTC decode: argmax per frame, collapse repeats,
    drop blanks (reference capability: fluid ctc_greedy_decoder).  Returns
    (decoded [B, Lmax] padded with -1, lengths [B])."""
    lp = log_probs._data if isinstance(log_probs, Tensor) else \
        jnp.asarray(log_probs)
    if lp.ndim != 3:
        raise ValueError("ctc_decode expects [T, B, C] log-probs/logits")
    T, B, C = lp.shape
    path = np.asarray(jnp.argmax(lp, axis=-1))        # [T, B]
    ilen = np.full(B, T) if input_lengths is None else \
        np.asarray(input_lengths._data if isinstance(input_lengths, Tensor)
                   else input_lengths)
    outs = []
    for b in range(B):
        seq = []
        prev = -1
        for t in range(int(ilen[b])):
            c = int(path[t, b])
            if c != blank and c != prev:
                seq.append(c)
            prev = c
        outs.append(seq)
    lmax = max((len(s) for s in outs), default=0)
    dec = np.full((B, max(lmax, 1)), -1, np.int64)
    for b, s in enumerate(outs):
        dec[b, :len(s)] = s
    return (wrap_array(jnp.asarray(dec)),
            wrap_array(jnp.asarray(np.asarray([len(s) for s in outs],
                                              np.int64))))
