"""Long-tail nn functionals (reference: ops.yaml + nn/functional rows with
no prior mapping — interpolation family, grid sampling, fold/unpool, extra
activations and losses).  MXU-friendly formulations: interpolation via
jax.image, grid_sample as a vectorized bilinear gather, fold as the im2col
transpose."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ...framework.dispatch import def_op
from ...framework.random import split_key
from ...framework.tensor import Tensor


def _pair(v):
    return (v, v) if isinstance(v, int) else tuple(v)


# ------------------------------------------------------------ interpolation
def _resize(x, size, method, antialias=False):
    out_shape = x.shape[:2] + tuple(size)
    return jax.image.resize(x, out_shape, method=method,
                            antialias=antialias)


def _linear_1d_align(x, out_size, axis):
    """Separable linear interpolation with align_corners=True semantics
    (corner samples map exactly; jax.image.resize only does half-pixel)."""
    n = x.shape[axis]
    if out_size == 1 or n == 1:
        idx0 = jnp.zeros(out_size, jnp.int32)
        return jnp.take(x, idx0, axis=axis)
    coords = jnp.arange(out_size) * ((n - 1) / (out_size - 1))
    lo = jnp.floor(coords).astype(jnp.int32)
    hi = jnp.minimum(lo + 1, n - 1)
    w = (coords - lo).astype(x.dtype)
    shape = [1] * x.ndim
    shape[axis] = out_size
    w = w.reshape(shape)
    return (jnp.take(x, lo, axis=axis) * (1 - w)
            + jnp.take(x, hi, axis=axis) * w)


def _linear_resize(x, sizes, align_corners):
    if not align_corners:
        return _resize(x, sizes, "linear" if len(sizes) == 1 else (
            "bilinear" if len(sizes) == 2 else "trilinear"))
    for i, s in enumerate(sizes):
        x = _linear_1d_align(x, s, x.ndim - len(sizes) + i)
    return x


@def_op("nearest_interp")
def nearest_interp(x, out_h, out_w):
    return _resize(x, (out_h, out_w), "nearest")


@def_op("bilinear_interp")
def bilinear_interp(x, out_h, out_w, align_corners=False):
    return _linear_resize(x, (out_h, out_w), align_corners)


@def_op("bicubic_interp")
def bicubic_interp(x, out_h, out_w, align_corners=False):
    if align_corners:
        raise NotImplementedError(
            "bicubic align_corners=True is not supported (jax.image.resize "
            "is half-pixel); use align_corners=False or bilinear")
    return _resize(x, (out_h, out_w), "bicubic")


@def_op("linear_interp")
def linear_interp(x, out_w, align_corners=False):
    return _linear_resize(x, (out_w,), align_corners)


@def_op("trilinear_interp")
def trilinear_interp(x, out_d, out_h, out_w, align_corners=False):
    return _linear_resize(x, (out_d, out_h, out_w), align_corners)


# -------------------------------------------------------------- grid sample
@def_op("affine_grid")
def affine_grid(theta, out_shape, align_corners=True):
    """reference: F.affine_grid — theta [N, 2, 3], out [N, H, W, 2]."""
    n, h, w = out_shape[0], out_shape[-2], out_shape[-1]
    if align_corners:
        ys = jnp.linspace(-1, 1, h)
        xs = jnp.linspace(-1, 1, w)
    else:
        ys = (jnp.arange(h) * 2 + 1) / h - 1
        xs = (jnp.arange(w) * 2 + 1) / w - 1
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)   # [H, W, 3]
    return jnp.einsum("hwk,nak->nhwa", base, theta)


@def_op("grid_sample")
def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True):
    """reference: F.grid_sample — x [N, C, H, W], grid [N, Ho, Wo, 2] in
    [-1, 1] (x then y)."""
    N, C, H, W = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1) * (W - 1) / 2
        fy = (gy + 1) * (H - 1) / 2
    else:
        fx = ((gx + 1) * W - 1) / 2
        fy = ((gy + 1) * H - 1) / 2

    def sample_one(feat, fx, fy):
        def at(yi, xi):
            if padding_mode == "border":
                yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                return feat[:, yc, xc]
            oob = (yi < 0) | (yi > H - 1) | (xi < 0) | (xi > W - 1)
            yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
            xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
            return jnp.where(oob, 0.0, feat[:, yc, xc])
        if mode == "nearest":
            return at(jnp.round(fy), jnp.round(fx))
        y0, x0 = jnp.floor(fy), jnp.floor(fx)
        ly, lx = fy - y0, fx - x0
        return (at(y0, x0) * (1 - ly) * (1 - lx)
                + at(y0, x0 + 1) * (1 - ly) * lx
                + at(y0 + 1, x0) * ly * (1 - lx)
                + at(y0 + 1, x0 + 1) * ly * lx)

    return jax.vmap(sample_one)(x, fx, fy)


# ------------------------------------------------------------- fold/unpool
@def_op("fold")
def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1):
    """reference: F.fold (col2im) — x [N, C*kh*kw, L] -> [N, C, H, W];
    overlaps sum (the transpose of unfold)."""
    oh, ow = _pair(output_sizes)
    kh, kw = _pair(kernel_sizes)
    sh, sw = _pair(strides)
    ph, pw = _pair(paddings)
    dh, dw = _pair(dilations)
    N = x.shape[0]
    C = x.shape[1] // (kh * kw)
    lh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    lw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    cols = x.reshape(N, C, kh, kw, lh, lw)
    out = jnp.zeros((N, C, oh + 2 * ph, ow + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            ys = i * dh
            xs = j * dw
            out = out.at[:, :, ys:ys + lh * sh:sh,
                         xs:xs + lw * sw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + oh, pw:pw + ow]


def _pool_out_size(n, k, s, p, ceil_mode):
    """Output extent, torch/paddle semantics: with ceil_mode the last
    window may run past the right edge but must START within
    input + left padding."""
    if ceil_mode:
        o = (n + 2 * p - k + s - 1) // s + 1
        if (o - 1) * s >= n + p:
            o -= 1
        return o
    return (n + 2 * p - k) // s + 1


@def_op("max_pool2d_with_index")
def max_pool2d_with_index(x, kernel_size, stride=None, padding=0,
                          ceil_mode=False):
    """Returns (pooled, flat argmax index into each image plane)."""
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride if stride is not None else kernel_size)
    ph, pw = _pair(padding)
    N, C, H, W = x.shape
    oh = _pool_out_size(H, kh, sh, ph, ceil_mode)
    ow = _pool_out_size(W, kw, sw, pw, ceil_mode)
    # ceil_mode: extra right-padding so the strided slicing below covers
    # every window (padded values are -inf and can never win the argmax)
    eh = max(0, (oh - 1) * sh + kh - (H + 2 * ph))
    ew = max(0, (ow - 1) * sw + kw - (W + 2 * pw))
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph + eh), (pw, pw + ew)),
                 constant_values=neg)
    # index map of the padded plane back to the original flat index
    iy = jnp.arange(H + 2 * ph + eh) - ph
    ix = jnp.arange(W + 2 * pw + ew) - pw
    flat_idx = (jnp.clip(iy[:, None], 0, H - 1) * W
                + jnp.clip(ix[None, :], 0, W - 1))
    vals, idxs = [], []
    for i in range(kh):
        for j in range(kw):
            patch = xp[:, :, i:i + oh * sh:sh, j:j + ow * sw:sw]
            pidx = flat_idx[i:i + oh * sh:sh, j:j + ow * sw:sw]
            vals.append(patch)
            idxs.append(jnp.broadcast_to(pidx, patch.shape))
    vals = jnp.stack(vals)
    idxs = jnp.stack(idxs)
    best = jnp.argmax(vals, axis=0)
    pooled = jnp.take_along_axis(vals, best[None], axis=0)[0]
    index = jnp.take_along_axis(idxs, best[None], axis=0)[0]
    return pooled, index.astype(jnp.int32)


@def_op("max_unpool2d")
def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    """reference: F.max_unpool2d — scatter pooled values back to their
    argmax positions."""
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride if stride is not None else kernel_size)
    N, C, H, W = x.shape
    if output_size is None:
        oh = (H - 1) * sh + kh - 2 * _pair(padding)[0]
        ow = (W - 1) * sw + kw - 2 * _pair(padding)[1]
    else:
        oh, ow = output_size[-2], output_size[-1]
    flat = jnp.zeros((N, C, oh * ow), x.dtype)
    # .set, not .add: overlapping windows sharing an argmax carry identical
    # values; the reference kernel overwrites rather than accumulating
    flat = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        indices.reshape(N, C, -1)].set(x.reshape(N, C, -1))
    return flat.reshape(N, C, oh, ow)


@def_op("lp_pool2d")
def lp_pool2d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False):
    kh, kw = _pair(kernel_size)
    sh, sw = _pair(stride if stride is not None else kernel_size)
    ph, pw = _pair(padding)
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    H, W = xp.shape[-2:]
    oh = (H - kh) // sh + 1
    ow = (W - kw) // sw + 1
    acc = 0.0
    for i in range(kh):
        for j in range(kw):
            acc = acc + jnp.abs(
                xp[:, :, i:i + oh * sh:sh, j:j + ow * sw:sw]) ** norm_type
    return acc ** (1.0 / norm_type)


@def_op("channel_shuffle")
def channel_shuffle(x, groups, data_format="NCHW"):
    if data_format == "NCHW":
        n, c, h, w = x.shape
        return x.reshape(n, groups, c // groups, h, w).transpose(
            0, 2, 1, 3, 4).reshape(n, c, h, w)
    n, h, w, c = x.shape
    return x.reshape(n, h, w, groups, c // groups).transpose(
        0, 1, 2, 4, 3).reshape(n, h, w, c)


# -------------------------------------------------------------- activations
@def_op("tanh_shrink")
def tanh_shrink(x):
    return x - jnp.tanh(x)


@def_op("thresholded_relu")
def thresholded_relu(x, threshold=1.0, value=0.0):
    return jnp.where(x > threshold, x, jnp.asarray(value, x.dtype))


@def_op("swiglu")
def swiglu(x, y=None):
    """reference: fused swiglu — silu(x) * y (y defaults to the second half
    of the last axis)."""
    if y is None:
        x, y = jnp.split(x, 2, axis=-1)
    return jax.nn.silu(x) * y


@def_op("rrelu_")
def _rrelu(x, lower, upper, training, key):
    if training:
        a = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
    else:
        a = (lower + upper) / 2.0
    return jnp.where(x >= 0, x, (a * x).astype(x.dtype))


def rrelu(x, lower=1.0 / 8, upper=1.0 / 3, training=True, name=None):
    return _rrelu(x, float(lower), float(upper), bool(training), split_key())


# ------------------------------------------------------------------- losses
@def_op("sigmoid_cross_entropy_with_logits")
def sigmoid_cross_entropy_with_logits(logits, labels, ignore_index=-100,
                                      normalize=False):
    loss = jnp.maximum(logits, 0) - logits * labels + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    mask = (labels != ignore_index).astype(loss.dtype)
    loss = loss * mask
    if normalize:
        loss = loss / jnp.maximum(mask.sum(), 1.0)
    return loss


@def_op("hinge_loss")
def hinge_loss(logits, labels):
    return jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)


@def_op("log_loss")
def log_loss(input, label, epsilon=1e-4):
    return -label * jnp.log(input + epsilon) - \
        (1 - label) * jnp.log(1 - input + epsilon)


@def_op("identity_loss")
def identity_loss(x, reduction="none"):
    if reduction in ("mean", 1):
        return jnp.mean(x)
    if reduction in ("sum", 2):
        return jnp.sum(x)
    return x


@def_op("hsigmoid_loss")
def hsigmoid_loss(x, label, weight, bias, path_table, path_code):
    """Hierarchical sigmoid along precomputed paths (reference:
    hsigmoid_loss with custom tree).  path_table [B, D]: node ids (-1 pad);
    path_code [B, D]: binary codes."""
    sel_w = weight[path_table]                     # [B, D, F]
    logits = jnp.einsum("bdf,bf->bd", sel_w, x)
    if bias is not None:
        logits = logits + bias[path_table][..., 0] if bias.ndim == 2 \
            else logits + bias[path_table]
    valid = (path_table >= 0).astype(logits.dtype)
    code = path_code.astype(logits.dtype)
    loss = jnp.maximum(logits, 0) - logits * code + \
        jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return (loss * valid).sum(axis=-1, keepdims=True)


@def_op("margin_cross_entropy")
def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0):
    """reference: margin_cross_entropy (ArcFace-style margins).
    cos(m1*theta + m2) - m3 applied to the target logit."""
    theta = jnp.arccos(jnp.clip(logits, -1 + 1e-7, 1 - 1e-7))
    target_theta = jnp.take_along_axis(theta, label[:, None], axis=-1)
    adj = jnp.cos(margin1 * target_theta + margin2) - margin3
    onehot = jax.nn.one_hot(label, logits.shape[-1], dtype=logits.dtype)
    out = jnp.where(onehot > 0, adj, logits) * scale
    logp = jax.nn.log_softmax(out, axis=-1)
    loss = -jnp.take_along_axis(logp, label[:, None], axis=-1)
    return loss, jax.nn.softmax(out, axis=-1)


@def_op("class_center_sample_")
def _class_center_sample(label, num_classes, num_samples, key):
    pos = jnp.zeros(num_classes, bool).at[label].set(True)
    noise = jax.random.uniform(key, (num_classes,))
    # positives first (noise - 1 < 0 <= noise), then random negatives
    order = jnp.argsort(jnp.where(pos, noise - 1.0, noise))
    sampled = jnp.sort(order[:num_samples])
    # remap labels into the sampled index space
    remap = jnp.zeros(num_classes, jnp.int64).at[sampled].set(
        jnp.arange(num_samples, dtype=jnp.int64))
    return remap[label], sampled.astype(jnp.int64)


def class_center_sample(label, num_classes, num_samples, group=None):
    """reference: class_center_sample — sample class centers for partial-fc
    style training; returns (remapped_label, sampled_class_centers)."""
    return _class_center_sample(label, int(num_classes), int(num_samples),
                                split_key())


# ---------------------------------------------------------- fused softmax
@def_op("fused_softmax_mask")
def fused_softmax_mask(x, mask):
    return jax.nn.softmax(x + mask, axis=-1)


@def_op("fused_softmax_mask_upper_triangle")
def fused_softmax_mask_upper_triangle(x):
    T = x.shape[-1]
    causal = jnp.tril(jnp.ones((x.shape[-2], T), bool))
    return jax.nn.softmax(jnp.where(causal, x, -1e9), axis=-1)


@def_op("pad3d")
def pad3d(x, paddings, mode="constant", value=0.0, data_format="NCDHW"):
    pl, pr, pt, pb, pf, pk = paddings   # w-l/r, h-top/bottom, d-front/back
    if data_format == "NCDHW":
        pads = ((0, 0), (0, 0), (pf, pk), (pt, pb), (pl, pr))
    elif data_format == "NDHWC":
        pads = ((0, 0), (pf, pk), (pt, pb), (pl, pr), (0, 0))
    else:
        raise ValueError(f"pad3d: unknown data_format {data_format!r}")
    if mode == "constant":
        return jnp.pad(x, pads, constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return jnp.pad(x, pads, mode=jmode)


def fractional_max_pool2d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference: F.fractional_max_pool2d — pseudo-random fractional
    pooling; with return_mask also the flat argmax per output cell."""
    from .pool_conv import _fractional_argmax_nd, _frac_u
    u = _frac_u(random_u)   # one draw shared by value and mask paths
    ks = None if kernel_size is None else _pair(kernel_size)
    out = _fractional_max_pool2d(x, output_size, ks, u)
    if return_mask:
        return out, _fractional_argmax_nd(x, _pair(output_size), u, ks)
    return out


@def_op("fractional_max_pool2d")
def _fractional_max_pool2d(x, output_size, kernel_size=None,
                           random_u=0.5):
    """Pseudo-random fractional pooling (Graham 2014): bin edges from u.
    Disjoint segment-max per axis without kernel_size (O(H*W) memory);
    overlapping [start, start+k) windows with it."""
    from .pool_conv import _frac_reduce_axis
    oh, ow = _pair(output_size)
    u = float(random_u)
    ks = (None, None) if kernel_size is None else _pair(kernel_size)
    for axis, o, k in zip((2, 3), (oh, ow), ks):
        x = _frac_reduce_axis(x, axis, o, u, k)
    return x


@def_op("affine_channel")
def affine_channel(x, scale, bias, data_format="NCHW"):
    if data_format == "NCHW":
        return x * scale[None, :, None, None] + bias[None, :, None, None]
    return x * scale + bias


@def_op("shuffle_channel")
def shuffle_channel(x, group=1):
    return channel_shuffle.raw_fn(x, group, "NCHW")


@def_op("bce_loss")
def bce_loss(input, label):
    eps = 1e-12
    return -(label * jnp.log(input + eps)
             + (1 - label) * jnp.log(1 - input + eps))


@def_op("kldiv_loss")
def kldiv_loss(x, target, reduction="mean", log_target=False):
    t = jnp.exp(target) if log_target else target
    loss = jnp.where(t > 0, t * (jnp.log(jnp.maximum(t, 1e-12)) - x), 0.0)
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "batchmean":
        return jnp.sum(loss) / x.shape[0]
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("logsigmoid")
def logsigmoid(x):
    return jax.nn.log_sigmoid(x)


@def_op("max_unpool3d")
def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None):
    ks = (kernel_size,) * 3 if isinstance(kernel_size, int) else kernel_size
    st = ks if stride is None else (
        (stride,) * 3 if isinstance(stride, int) else stride)
    N, C, D, H, W = x.shape
    if output_size is None:
        od = (D - 1) * st[0] + ks[0]
        oh = (H - 1) * st[1] + ks[1]
        ow = (W - 1) * st[2] + ks[2]
    else:
        od, oh, ow = output_size[-3:]
    flat = jnp.zeros((N, C, od * oh * ow), x.dtype)
    flat = flat.at[
        jnp.arange(N)[:, None, None],
        jnp.arange(C)[None, :, None],
        indices.reshape(N, C, -1)].set(x.reshape(N, C, -1))
    return flat.reshape(N, C, od, oh, ow)


@def_op("l2_normalize")
def l2_normalize(x, axis=-1, epsilon=1e-12):
    return x / jnp.sqrt(jnp.maximum(
        jnp.sum(x * x, axis=axis, keepdims=True), epsilon))


@def_op("ctc_align")
def ctc_align(input, blank=0, merge_repeated=True):
    """Greedy path collapse mask (padded with -1), jittable form."""
    prev = jnp.concatenate([jnp.full((input.shape[0], 1), -1, input.dtype),
                            input[:, :-1]], axis=1)
    keep = (input != blank) & ((input != prev) | (not merge_repeated))
    return jnp.where(keep, input, -1)
