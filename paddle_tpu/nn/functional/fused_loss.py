"""Chunked fused linear + softmax cross-entropy.

The LM-head loss is the largest single activation in decoder pretraining:
``[batch*seq, vocab]`` logits in f32 (the bench headline config: 8*1024 x
32000 = 1.05 GB) written to HBM in the forward and read back (plus the
same-size softmax gradient) in the backward.  On TPU the matmul FLOPs are
cheap next to that HBM traffic.  This op never materializes the full
logits: a ``lax.scan`` over row chunks computes each chunk's logits in
VMEM-sized pieces, reduces them to the scalar loss, and the custom VJP
recomputes each chunk's logits in the backward (one extra ``N*H*V``
matmul — the classic remat trade, same recipe as jax.checkpoint but
specialized so that dW accumulates across chunks in f32).

Reference parity: the reference fuses this region too, on the same
motivation — paddle/phi/kernels/fusion/ (fused softmax/CE family) and the
mp variant c_softmax_with_cross_entropy_op.cu (vocab-sharded CE, mapped
in distributed/fleet/mp_layers.py).  This file is the single-chip fusion.

Numerics contract: identical math to ``F.cross_entropy(hidden @ W + b,
labels)`` with reduction='mean' over non-ignored rows, computed in f32
regardless of input dtype (the unfused path casts logits to f32 the same
way in the bench loss).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _chunk_logits(h_chunk, weight, bias):
    """[c, H] @ [H, V] -> [c, V] in f32 on the MXU."""
    logits = jnp.dot(h_chunk, weight, preferred_element_type=jnp.float32)
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    return logits


def _fwd_scan(hidden, weight, bias, labels, valid, chunk_rows):
    n_pad = hidden.shape[0]
    n_chunks = n_pad // chunk_rows
    h_c = hidden.reshape(n_chunks, chunk_rows, hidden.shape[1])
    l_c = labels.reshape(n_chunks, chunk_rows)
    v_c = valid.reshape(n_chunks, chunk_rows)

    def body(acc, inp):
        h, lab, val = inp
        logits = _chunk_logits(h, weight, bias)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, lab[:, None].astype(jnp.int32), axis=1)[:, 0]
        loss = jnp.where(val, lse - picked, 0.0)
        return acc + jnp.sum(loss), None

    total, _ = lax.scan(body, jnp.float32(0.0), (h_c, l_c, v_c))
    return total


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused_linear_ce(hidden, weight, bias, labels, ignore_index,
                     chunk_rows):
    loss, _ = _fused_linear_ce_fwd(hidden, weight, bias, labels,
                                   ignore_index, chunk_rows)
    return loss


def _pad_rows(x, chunk_rows, fill=0):
    n = x.shape[0]
    pad = (-n) % chunk_rows
    if pad:
        width = [(0, pad)] + [(0, 0)] * (x.ndim - 1)
        x = jnp.pad(x, width, constant_values=fill)
    return x


def _fused_linear_ce_fwd(hidden, weight, bias, labels, ignore_index,
                         chunk_rows):
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    h_p = _pad_rows(hidden, chunk_rows)
    l_p = _pad_rows(safe, chunk_rows)
    v_p = _pad_rows(valid, chunk_rows, fill=False)
    total = _fwd_scan(h_p, weight, bias, l_p, v_p, chunk_rows)
    n_valid = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    loss = total / n_valid
    return loss, (hidden, weight, bias, safe, valid, n_valid)


def _fused_linear_ce_bwd(ignore_index, chunk_rows, res, g):
    hidden, weight, bias, safe, valid, n_valid = res
    n, h_dim = hidden.shape
    h_p = _pad_rows(hidden, chunk_rows)
    l_p = _pad_rows(safe, chunk_rows)
    v_p = _pad_rows(valid, chunk_rows, fill=False)
    n_pad = h_p.shape[0]
    n_chunks = n_pad // chunk_rows
    h_c = h_p.reshape(n_chunks, chunk_rows, h_dim)
    l_c = l_p.reshape(n_chunks, chunk_rows)
    v_c = v_p.reshape(n_chunks, chunk_rows)
    scale = g / n_valid                       # d(mean-loss)/d(row-loss)
    vocab = weight.shape[1]

    def body(dw_acc, inp):
        h, lab, val = inp
        logits = _chunk_logits(h, weight, bias)
        p = jax.nn.softmax(logits, axis=-1)
        delta = p - jax.nn.one_hot(lab, vocab, dtype=p.dtype)
        delta = delta * (val.astype(p.dtype) * scale)[:, None]
        dh = jnp.dot(delta, weight.astype(jnp.float32).T)
        dw_acc = dw_acc + jnp.dot(h.astype(jnp.float32).T, delta)
        return dw_acc, (dh, jnp.sum(delta, axis=0))

    dw0 = jnp.zeros((h_dim, vocab), jnp.float32)
    dw, (dh_c, db_c) = lax.scan(body, dw0, (h_c, l_c, v_c))
    dh = dh_c.reshape(n_pad, h_dim)[:n].astype(hidden.dtype)
    dw = dw.astype(weight.dtype)
    db = jnp.sum(db_c, axis=0).astype(bias.dtype) \
        if bias is not None else None
    return dh, dw, db, None


_fused_linear_ce.defvjp(_fused_linear_ce_fwd, _fused_linear_ce_bwd)


def fused_linear_cross_entropy_raw(hidden, weight, labels, bias=None,
                                   ignore_index=-100, chunk_rows=1024):
    """Mean CE of ``hidden @ weight (+ bias)`` against ``labels`` without
    materializing logits.  hidden: [..., H] (leading dims flattened),
    weight: [H, V], labels: [...] int.  Returns a f32 scalar."""
    h2 = hidden.reshape(-1, hidden.shape[-1])
    l1 = labels.reshape(-1)
    chunk_rows = min(chunk_rows, max(h2.shape[0], 1))
    return _fused_linear_ce(h2, weight, bias, l1, int(ignore_index),
                            int(chunk_rows))
