"""Loss long tail: probabilistic NLLs, margin family, metric-learning,
RNN-T, adaptive log-softmax.

Capability parity: python/paddle/nn/functional/loss.py in the reference
(gaussian_nll_loss, poisson_nll_loss, soft_margin_loss,
multi_label_soft_margin_loss, multi_margin_loss,
triplet_margin_with_distance_loss, dice_loss, npair_loss,
sigmoid_focal_loss, rnnt_loss, adaptive_log_softmax_with_loss,
pairwise_distance from distance.py).

TPU-native notes: rnnt_loss is a ``lax.scan`` over the T axis carrying one
U-row of the forward lattice (the reference wraps the warprnnt CUDA
kernel); everything differentiates through jax autodiff — no hand-written
backward kernels.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from ...framework.dispatch import def_op


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


@def_op("gaussian_nll_loss")
def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    """reference: F.gaussian_nll_loss — NLL of label under
    N(input, variance), variance clamped below at epsilon."""
    var = jnp.maximum(variance, epsilon)
    loss = 0.5 * (jnp.log(var) + (input - label) ** 2 / var)
    if full:
        loss = loss + 0.5 * math.log(2 * math.pi)
    return _reduce(loss, reduction)


@def_op("poisson_nll_loss")
def poisson_nll_loss(input, label, log_input=True, full=False,
                     epsilon=1e-8, reduction="mean", name=None):
    """reference: F.poisson_nll_loss — NLL of label under
    Poisson(exp(input)) (log_input) or Poisson(input)."""
    if log_input:
        loss = jnp.exp(input) - label * input
    else:
        loss = input - label * jnp.log(input + epsilon)
    if full:
        # Stirling approximation for label! where label > 1
        stirling = (label * jnp.log(label) - label
                    + 0.5 * jnp.log(2 * math.pi * label))
        loss = loss + jnp.where(label > 1, stirling, 0.0)
    return _reduce(loss, reduction)


@def_op("soft_margin_loss")
def soft_margin_loss(input, label, reduction="mean", name=None):
    """reference: F.soft_margin_loss — log(1 + exp(-label * input))."""
    loss = jnp.log1p(jnp.exp(-label.astype(input.dtype) * input))
    return _reduce(loss, reduction)


@def_op("multi_label_soft_margin_loss")
def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    y = label.astype(input.dtype)
    logsig = jax.nn.log_sigmoid
    loss = -(y * logsig(input) + (1 - y) * logsig(-input))
    if weight is not None:
        loss = loss * weight
    loss = jnp.mean(loss, axis=-1)
    return _reduce(loss, reduction)


@def_op("multi_margin_loss")
def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    """reference: F.multi_margin_loss — mean_j max(0, margin - x_y + x_j)^p
    over j != y."""
    n, c = input.shape
    xy = jnp.take_along_axis(input, label[:, None], axis=1)
    viol = jnp.maximum(0.0, margin - xy + input) ** p
    if weight is not None:
        viol = viol * weight[label][:, None]
    # zero out the true-class column
    onehot = jax.nn.one_hot(label, c, dtype=input.dtype)
    loss = jnp.sum(viol * (1 - onehot), axis=1) / c
    return _reduce(loss, reduction)


@def_op("triplet_margin_with_distance_loss")
def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function if distance_function is not None else \
        (lambda a, b: jnp.linalg.norm(a - b, axis=-1))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    loss = jnp.maximum(0.0, dp - dn + margin)
    return _reduce(loss, reduction)


@def_op("pairwise_distance")
def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    """reference: F.pairwise_distance (distance.py) — ||x - y + eps||_p
    along the last axis."""
    d = x - y + epsilon
    out = jnp.sum(jnp.abs(d) ** p, axis=-1) ** (1.0 / p)
    if keepdim:
        out = out[..., None]
    return out


@def_op("dice_loss")
def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference: F.dice_loss — input [N, ..., C] class probabilities,
    label [N, ..., 1] int labels."""
    c = input.shape[-1]
    onehot = jax.nn.one_hot(label[..., 0], c, dtype=input.dtype)
    flat_in = input.reshape(input.shape[0], -1)
    flat_lab = onehot.reshape(onehot.shape[0], -1)
    inter = jnp.sum(flat_in * flat_lab, axis=1)
    union = jnp.sum(flat_in, axis=1) + jnp.sum(flat_lab, axis=1)
    return jnp.mean(1.0 - (2.0 * inter + epsilon) / (union + epsilon))


@def_op("npair_loss")
def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """reference: F.npair_loss — similarity CE + L2 on embeddings."""
    reg = (jnp.mean(jnp.sum(anchor ** 2, axis=1))
           + jnp.mean(jnp.sum(positive ** 2, axis=1))) * 0.25 * l2_reg
    sim = anchor @ positive.T                      # [N, N]
    same = (labels[:, None] == labels[None, :]).astype(sim.dtype)
    targets = same / jnp.sum(same, axis=1, keepdims=True)
    logp = jax.nn.log_softmax(sim, axis=1)
    ce = -jnp.mean(jnp.sum(targets * logp, axis=1))
    return ce + reg


@def_op("sigmoid_focal_loss")
def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25,
                       gamma=2.0, reduction="sum", name=None):
    """reference: F.sigmoid_focal_loss (RetinaNet focal loss)."""
    p = jax.nn.sigmoid(logit)
    y = label.astype(logit.dtype)
    ce = jnp.maximum(logit, 0) - logit * y + jnp.log1p(
        jnp.exp(-jnp.abs(logit)))
    p_t = p * y + (1 - p) * (1 - y)
    loss = ce * ((1 - p_t) ** gamma)
    if alpha >= 0:
        loss = loss * (alpha * y + (1 - alpha) * (1 - y))
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce(loss, reduction)


# ------------------------------------------------------------------ RNN-T
@def_op("rnnt_loss")
def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.001, reduction="mean", name=None):
    """RNN-transducer loss (Graves 2012). reference: F.rnnt_loss wrapping
    the warprnnt CUDA kernel (paddle/phi/kernels/gpu/warprnnt_kernel.cu);
    here the forward lattice runs as a ``lax.scan`` over T carrying one
    U-row of log-alphas, and the gradient falls out of autodiff.

    input:  [B, Tmax, Umax+1, V] raw logits (log_softmax applied inside).
    label:  [B, Umax] int targets.
    """
    logp = jax.nn.log_softmax(input, axis=-1)
    B, T, U1, V = logp.shape
    U = U1 - 1
    neg_inf = jnp.asarray(-1e30, logp.dtype)

    # per-(t,u) transition log-probs
    blank_lp = logp[..., blank]                               # [B, T, U+1]
    lab = jnp.minimum(label, V - 1)
    emit_lp = jnp.take_along_axis(
        logp[:, :, :U, :], lab[:, None, :, None].repeat(T, 1), axis=-1
    )[..., 0]                                                  # [B, T, U]
    if fastemit_lambda:
        # FastEmit (Yu et al. 2021): up-weight the label-emission path
        emit_lp = emit_lp + math.log1p(fastemit_lambda)

    u_idx = jnp.arange(U1)
    # the horizontal (t-1 -> t) move consumes the blank at column t-1
    blank_prev = jnp.concatenate(
        [jnp.zeros((B, 1, U1), logp.dtype), blank_lp[:, :-1, :]], axis=1)

    def step(alpha_prev, xs):
        """alpha column t from column t-1: horizontal blank move from the
        previous column, then an in-column sweep over u emissions.
        alpha[t, u] = logaddexp(alpha[t-1, u] + blank[t-1, u],
                                alpha[t, u-1] + emit[t, u-1])"""
        blank_tm1, emit_t, first = xs          # [B, U+1], [B, U], bool
        horiz = jnp.where(first, jnp.where(u_idx == 0, 0.0, neg_inf),
                          alpha_prev + blank_tm1)

        def body(carry, idx):
            # carry: alpha[t, u-1] for all B
            h = horiz[:, idx]                  # [B]
            e = emit_t[:, jnp.maximum(idx - 1, 0)]   # [B] emit from u-1
            val = jnp.where(idx == 0, h, jnp.logaddexp(h, carry + e))
            return val, val

        _, cols = lax.scan(body, jnp.full((B,), neg_inf, logp.dtype),
                           jnp.arange(U1))
        alpha_t = jnp.moveaxis(cols, 0, 1)     # [B, U+1]
        return alpha_t, alpha_t

    first_flags = jnp.arange(T) == 0
    _, alphas = lax.scan(
        step, jnp.full((B, U1), neg_inf, logp.dtype),
        (jnp.moveaxis(blank_prev, 1, 0), jnp.moveaxis(emit_lp, 1, 0),
         first_flags))
    alphas = jnp.moveaxis(alphas, 0, 1)        # [B, T, U+1]

    t_last = jnp.clip(input_lengths - 1, 0, T - 1)
    u_last = jnp.clip(label_lengths, 0, U)
    a_end = alphas[jnp.arange(B), t_last, u_last]
    lp_end = blank_lp[jnp.arange(B), t_last, u_last]
    nll = -(a_end + lp_end)
    return _reduce(nll, reduction)


# ----------------------------------------------- adaptive log softmax
@def_op("adaptive_log_softmax_with_loss")
def adaptive_log_softmax_with_loss(input, label, head_weight, tail_weights,
                                   cutoffs, head_bias=None, name=None):
    """reference: F.adaptive_log_softmax_with_loss — two-level softmax:
    a head over [frequent classes + one slot per tail cluster], then a
    per-cluster tail projection (Grave et al. 2017).

    Computes every cluster's log-prob for every row (TPU-friendly dense
    compute; rows select their cluster by mask) — returns (out, loss)
    with out[i] = log p(label_i | input_i).
    """
    cutoffs = list(cutoffs)
    shortlist = cutoffs[0]
    n_clusters = len(tail_weights)
    head_out = input @ head_weight
    if head_bias is not None:
        head_out = head_out + head_bias
    head_logp = jax.nn.log_softmax(head_out, axis=-1)   # [N, shortlist+K]

    in_short = label < shortlist
    short_lp = jnp.take_along_axis(
        head_logp, jnp.minimum(label, shortlist - 1)[:, None], axis=1)[:, 0]

    out = jnp.where(in_short, short_lp, 0.0)
    for k in range(n_clusters):
        lo, hi = cutoffs[k], cutoffs[k + 1]
        w = tail_weights[k]
        if isinstance(w, (list, tuple)):    # factorized [proj, out] pair
            tail_out = (input @ w[0]) @ w[1]
        else:
            tail_out = input @ w
        tail_lp = jax.nn.log_softmax(tail_out, axis=-1)  # [N, hi-lo]
        cluster_lp = head_logp[:, shortlist + k]
        rel = jnp.clip(label - lo, 0, hi - lo - 1)
        lp = cluster_lp + jnp.take_along_axis(
            tail_lp, rel[:, None], axis=1)[:, 0]
        out = jnp.where((label >= lo) & (label < hi), lp, out)
    loss = -jnp.mean(out)
    return out, loss
