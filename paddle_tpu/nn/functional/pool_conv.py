"""3-D / adaptive / Lp / fractional pooling + 1-D/3-D transpose convs.

Capability parity: python/paddle/nn/functional/pooling.py (max_pool3d,
avg_pool3d, adaptive_avg_pool3d, adaptive_max_pool1d/3d, lp_pool1d,
fractional_max_pool3d, max_unpool1d) and conv.py (conv1d_transpose,
conv3d_transpose).  All windows lower to one ``lax.reduce_window`` /
``conv_general_dilated`` — XLA tiles them onto the TPU vector/matrix units.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...framework.dispatch import def_op
# the parent package binds these before importing this module (see the
# import at the bottom of functional/__init__.py)
from . import _pool, _norm_tuple, _conv_padding
from .extra import max_unpool2d


# ------------------------------------------------------------ 3-D pooling
@def_op("max_pool3d")
def _max_pool3d(x, ksize, stride, padding, channel_last, ceil_mode):
    return _pool(x, ksize, stride, padding, lax.max, -jnp.inf, 3,
                 channel_last, ceil_mode)


@def_op("max_pool3d_with_index")
def max_pool3d_with_index(x, kernel_size, stride=None, padding=0,
                          ceil_mode=False):
    """(pooled, flat argmax into each D*H*W volume) — the 3-D analog of
    max_pool2d_with_index (reference phi max_pool3d_with_index kernel)."""
    from .extra import _pool_out_size
    kd, kh, kw = _norm_tuple(kernel_size, 3)
    sd, sh, sw = _norm_tuple(stride if stride is not None else kernel_size, 3)
    pd, ph, pw = _norm_tuple(padding, 3)
    N, C, D, H, W = x.shape
    od = _pool_out_size(D, kd, sd, pd, ceil_mode)
    oh = _pool_out_size(H, kh, sh, ph, ceil_mode)
    ow = _pool_out_size(W, kw, sw, pw, ceil_mode)
    ed = max(0, (od - 1) * sd + kd - (D + 2 * pd))
    eh = max(0, (oh - 1) * sh + kh - (H + 2 * ph))
    ew = max(0, (ow - 1) * sw + kw - (W + 2 * pw))
    neg = jnp.asarray(-jnp.inf, x.dtype)
    xp = jnp.pad(x, ((0, 0), (0, 0), (pd, pd + ed), (ph, ph + eh),
                     (pw, pw + ew)), constant_values=neg)
    iz = jnp.clip(jnp.arange(D + 2 * pd + ed) - pd, 0, D - 1)
    iy = jnp.clip(jnp.arange(H + 2 * ph + eh) - ph, 0, H - 1)
    ix = jnp.clip(jnp.arange(W + 2 * pw + ew) - pw, 0, W - 1)
    flat_idx = (iz[:, None, None] * (H * W) + iy[None, :, None] * W
                + ix[None, None, :])
    vals, idxs = [], []
    for a in range(kd):
        for i in range(kh):
            for j in range(kw):
                patch = xp[:, :, a:a + od * sd:sd, i:i + oh * sh:sh,
                           j:j + ow * sw:sw]
                pidx = flat_idx[a:a + od * sd:sd, i:i + oh * sh:sh,
                                j:j + ow * sw:sw]
                vals.append(patch)
                idxs.append(jnp.broadcast_to(pidx, patch.shape))
    vals = jnp.stack(vals)
    idxs = jnp.stack(idxs)
    best = jnp.argmax(vals, axis=0)
    pooled = jnp.take_along_axis(vals, best[None], axis=0)[0]
    index = jnp.take_along_axis(idxs, best[None], axis=0)[0]
    return pooled, index.astype(jnp.int32)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    if return_mask:
        from ...tensor.manipulation import transpose
        if data_format == "NDHWC":
            pooled, idx = max_pool3d_with_index(
                transpose(x, [0, 4, 1, 2, 3]), kernel_size, stride, padding,
                ceil_mode)
            return transpose(pooled, [0, 2, 3, 4, 1]), \
                transpose(idx, [0, 2, 3, 4, 1])
        return max_pool3d_with_index(x, kernel_size, stride, padding,
                                     ceil_mode)
    return _max_pool3d(x, kernel_size, stride, padding,
                       data_format == "NDHWC", ceil_mode)


@def_op("avg_pool3d")
def _avg_pool3d(x, ksize, stride, padding, channel_last, ceil_mode, cip,
                divisor):
    out = _pool(x, ksize, stride, padding, None, None, 3, channel_last,
                ceil_mode, cip, is_avg=True)
    if divisor is not None:
        ks = _norm_tuple(ksize, 3)
        out = out * (float(np.prod(ks)) / float(divisor))
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    cip = not exclusive or divisor_override is not None
    return _avg_pool3d(x, kernel_size, stride, padding,
                       data_format == "NDHWC", ceil_mode, cip,
                       divisor_override)


# ------------------------------------------------------- adaptive pooling
def _adaptive_segments(in_size, out_size):
    starts = (np.arange(out_size) * in_size) // out_size
    ends = ((np.arange(out_size) + 1) * in_size + out_size - 1) // out_size
    return starts, ends


def _adaptive_reduce(arr, axis, out_size, reduce_fn):
    starts, ends = _adaptive_segments(arr.shape[axis], out_size)
    segs = [reduce_fn(lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                      axis=axis, keepdims=True)
            for s, e in zip(starts, ends)]
    return jnp.concatenate(segs, axis=axis)


@def_op("adaptive_avg_pool3d_")
def _adaptive_avg_pool3d(x, out_dhw, channel_last):
    if channel_last:
        x = jnp.moveaxis(x, -1, 1)
    for axis, o in zip((2, 3, 4), out_dhw):
        x = _adaptive_reduce(x, axis, o, jnp.mean)
    if channel_last:
        x = jnp.moveaxis(x, 1, -1)
    return x


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_avg_pool3d(x, _norm_tuple(output_size, 3),
                                data_format == "NDHWC")


@def_op("adaptive_max_pool3d_")
def _adaptive_max_pool3d(x, out_dhw):
    for axis, o in zip((2, 3, 4), out_dhw):
        x = _adaptive_reduce(x, axis, o, jnp.max)
    return x


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_max_pool3d(x, _norm_tuple(output_size, 3))
    if not return_mask:
        return out
    return out, _adaptive_argmax_nd(x, _norm_tuple(output_size, 3))


def _cells_argmax(x, seg):
    """Flat index (into the trailing spatial volume) of the max of each
    output cell, for arbitrary per-axis (starts, ends) partitions — brute
    force over cells; cell counts are small by construction."""
    import itertools
    spatial = x.shape[2:]
    out_sizes = tuple(len(s) for s, _ in seg)
    idx_grid = jnp.arange(int(np.prod(spatial))).reshape(spatial)
    cells = []
    for cell in itertools.product(*[range(o) for o in out_sizes]):
        slc = tuple(slice(int(seg[d][0][c]), int(seg[d][1][c]))
                    for d, c in enumerate(cell))
        region = x[(slice(None), slice(None)) + slc].reshape(
            x.shape[0], x.shape[1], -1)
        ridx = idx_grid[slc].reshape(-1)
        cells.append(ridx[jnp.argmax(region, axis=-1)])
    out = jnp.stack(cells, axis=-1)
    return out.reshape(x.shape[:2] + out_sizes).astype(jnp.int32)


@def_op("adaptive_argmax_nd")
def _adaptive_argmax_nd(x, out_sizes):
    seg = [_adaptive_segments(n, o)
           for n, o in zip(x.shape[2:], out_sizes)]
    return _cells_argmax(x, seg)


def _frac_segments(inp, out, u, kernel=None):
    """Fractional-pooling partition of [0, inp) into `out` bins (the same
    start formula as the segment-max impl in extra.py).  With ``kernel``
    given, windows overlap: [start, start+kernel) instead of the disjoint
    [start_i, start_{i+1})."""
    alpha = inp / out
    starts = np.minimum(np.floor(alpha * (np.arange(out) + u)).astype(int),
                        inp - 1)
    starts[0] = 0
    if kernel is not None:
        # pin the last window to the input end (Graham 2014 interval
        # generation) so trailing rows are always covered
        starts[-1] = max(inp - int(kernel), 0)
        ends = np.minimum(starts + int(kernel), inp)
    else:
        ends = np.append(starts[1:], inp)
    return starts, ends


def _frac_u(random_u):
    """The pseudo-random offset u ∈ [0, 1): the caller's deterministic value
    (test mode) or a fresh draw from the framework RNG (reference: phi
    fractional pool kernels draw per call when random_u is unset)."""
    if random_u is not None:
        return float(random_u)
    import jax as _jax
    from ...framework.random import split_key
    return float(_jax.random.uniform(split_key(), ()))


@def_op("fractional_argmax_nd")
def _fractional_argmax_nd(x, out_sizes, u, kernel_sizes=None):
    if kernel_sizes is None:
        kernel_sizes = (None,) * len(out_sizes)
    seg = [_frac_segments(n, o, u, k)
           for n, o, k in zip(x.shape[2:], out_sizes, kernel_sizes)]
    return _cells_argmax(x, seg)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    o = _norm_tuple(output_size, 1)[0]
    out = _adaptive_reduce_op(x, o)
    if not return_mask:
        return out
    return out, _adaptive_argmax_nd(x, (o,))


@def_op("adaptive_max_pool1d_")
def _adaptive_reduce_op(x, out_size):
    return _adaptive_reduce(x, 2, out_size, jnp.max)


# ------------------------------------------------------------- Lp pooling
def lp_pool1d(x, norm_type, kernel_size, stride=None, padding=0,
              ceil_mode=False, data_format="NCL", name=None):
    from .extra import lp_pool2d
    k = _norm_tuple(kernel_size, 1)[0]
    s = _norm_tuple(stride if stride is not None else kernel_size, 1)[0]
    p = _norm_tuple(padding, 1)[0]
    out = lp_pool2d(x[..., None], norm_type, (k, 1), (s, 1), (p, 0),
                    ceil_mode)
    return out[..., 0]


# ---------------------------------------------------- fractional pooling
def fractional_max_pool3d(x, output_size, kernel_size=None, random_u=None,
                          return_mask=False, name=None):
    """reference: F.fractional_max_pool3d; with return_mask also the flat
    argmax per output cell."""
    u = _frac_u(random_u)   # one draw shared by value and mask paths
    ks = None if kernel_size is None else _norm_tuple(kernel_size, 3)
    out = _fractional_max_pool3d(x, output_size, ks, u)
    if return_mask:
        return out, _fractional_argmax_nd(x, _norm_tuple(output_size, 3),
                                          u, ks)
    return out


@def_op("fractional_max_pool3d")
def _fractional_max_pool3d(x, output_size, kernel_size=None, random_u=0.5):
    """3-D pseudo-random fractional pooling — per-axis reduction, the same
    O(D*H*W) scheme as the 2-D op (reference phi fractional_max_pool3d
    kernel).  Disjoint segments without kernel_size; overlapping
    [start, start+k) windows with it."""
    od, oh, ow = _norm_tuple(output_size, 3)
    u = float(random_u)
    ks = (None,) * 3 if kernel_size is None else _norm_tuple(kernel_size, 3)
    for axis, o, k in zip((2, 3, 4), (od, oh, ow), ks):
        x = _frac_reduce_axis(x, axis, o, u, k)
    return x


def _frac_reduce_axis(arr, axis, out, u, kernel=None):
    """Max-reduce one spatial axis into `out` fractional bins."""
    inp = arr.shape[axis]
    if kernel is None:
        starts, _ = _frac_segments(inp, out, u)
        ids = jnp.searchsorted(jnp.asarray(starts), jnp.arange(inp),
                               side="right") - 1
        m = jnp.moveaxis(arr, axis, 0)
        red = jax.ops.segment_max(m, jnp.clip(ids, 0, out - 1),
                                  num_segments=out)
        return jnp.moveaxis(red, 0, axis)
    starts, ends = _frac_segments(inp, out, u, kernel)
    idx = np.minimum(starts[:, None] + np.arange(int(kernel))[None, :],
                     ends[:, None] - 1)                    # [out, k]
    m = jnp.moveaxis(arr, axis, 0)                         # [inp, ...]
    g = m[jnp.asarray(idx)]                                # [out, k, ...]
    return jnp.moveaxis(g.max(axis=1), 0, axis)


# --------------------------------------------------------------- unpool
def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    """reference: F.max_unpool1d — scatter back along L via the 2-D op
    with a singleton W axis (flat plane index == L index when W=1)."""
    if output_size is not None:
        output_size = tuple(output_size) + (1,)
    k = _norm_tuple(kernel_size, 1)[0]
    s = _norm_tuple(stride if stride is not None else kernel_size, 1)[0]
    p = _norm_tuple(padding, 1)[0]
    out = max_unpool2d(x[..., None], indices[..., None], (k, 1), (s, 1),
                       (p, 0), output_size)
    return out[..., 0]


# ------------------------------------------------------- transpose convs
def _conv_transpose_nd(x, weight, bias, stride, padding, output_padding,
                       dilation, groups, channel_last, ndim):
    """General N-D transpose conv: flip + swap the kernel and run a
    dilated-LHS forward conv (what the reference's conv_transpose kernels
    do on the backward-data path)."""
    strides = _norm_tuple(stride, ndim)
    dil = _norm_tuple(dilation, ndim)
    opad = _norm_tuple(output_padding, ndim)
    k = weight.shape[2:]
    pads = _conv_padding(padding, ndim)
    sp = "DHW"[3 - ndim:]
    lhs_spec = ("N" + sp + "C") if channel_last else ("NC" + sp)
    if isinstance(pads, str):
        if pads == "VALID":
            pads = [(0, 0)] * ndim
        else:   # SAME
            w = weight
            if groups > 1:
                xs = jnp.split(x, groups, axis=-1 if channel_last else 1)
                ws = jnp.split(w, groups, axis=0)
                outs = [lax.conv_transpose(
                    xi, jnp.moveaxis(wi, (0, 1), (ndim, ndim + 1)),
                    strides=strides, padding="SAME", rhs_dilation=dil,
                    dimension_numbers=(lhs_spec, sp + "IO", lhs_spec))
                    for xi, wi in zip(xs, ws)]
                out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
            else:
                out = lax.conv_transpose(
                    x, jnp.moveaxis(w, (0, 1), (ndim, ndim + 1)),
                    strides=strides, padding="SAME", rhs_dilation=dil,
                    dimension_numbers=(lhs_spec, sp + "IO", lhs_spec))
            return _add_bias(out, bias, channel_last)

    eff = [(dil[i] * (k[i] - 1) - pads[i][0],
            dil[i] * (k[i] - 1) - pads[i][1] + opad[i]) for i in range(ndim)]
    flip_axes = tuple(range(2, 2 + ndim))
    wt = jnp.flip(weight, flip_axes)             # [in, out/g, *k] flipped
    dn = lax.conv_dimension_numbers(
        x.shape, (weight.shape[1], weight.shape[0]) + tuple(k),
        (lhs_spec, "OI" + sp, lhs_spec))
    if groups > 1:
        xs = jnp.split(x, groups, axis=-1 if channel_last else 1)
        ws = jnp.split(wt, groups, axis=0)
        outs = [lax.conv_general_dilated(
            xi, wi.swapaxes(0, 1), window_strides=(1,) * ndim, padding=eff,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)
            for xi, wi in zip(xs, ws)]
        out = jnp.concatenate(outs, axis=-1 if channel_last else 1)
    else:
        out = lax.conv_general_dilated(
            x, wt.swapaxes(0, 1), window_strides=(1,) * ndim, padding=eff,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn)
    return _add_bias(out, bias, channel_last)


def opad_from_output_size(output_size, in_spatial, stride, padding,
                          dilation, k, ndim):
    """Derive per-axis output_padding from a requested output_size
    (reference: conv_transpose's output_size contract — the requested
    length must be one of the stride-ambiguous valid lengths)."""
    strides = _norm_tuple(stride, ndim)
    dil = _norm_tuple(dilation, ndim)
    pads = _conv_padding(padding, ndim)
    if isinstance(pads, str):
        raise ValueError(
            "output_size cannot be combined with string padding")
    out_sp = _norm_tuple(output_size, ndim)
    opad = []
    for i in range(ndim):
        minimal = ((in_spatial[i] - 1) * strides[i] - pads[i][0]
                   - pads[i][1] + dil[i] * (k[i] - 1) + 1)
        op = int(out_sp[i]) - minimal
        if not 0 <= op < max(strides[i], dil[i]):
            raise ValueError(
                f"output_size[{i}]={out_sp[i]} invalid: must be in "
                f"[{minimal}, {minimal + max(strides[i], dil[i]) - 1}]")
        opad.append(op)
    return tuple(opad)


def _add_bias(out, bias, channel_last):
    if bias is None:
        return out
    shape = [1] * out.ndim
    shape[out.ndim - 1 if channel_last else 1] = bias.shape[0]
    return out + bias.reshape(shape)


@def_op("conv1d_transpose")
def _conv1d_transpose(x, weight, bias, stride, padding, output_padding,
                      dilation, groups, channel_last):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups,
                              channel_last, 1)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    channel_last = data_format == "NLC"
    if output_size is not None:
        in_sp = (x.shape[1],) if channel_last else (x.shape[2],)
        output_padding = opad_from_output_size(
            output_size, in_sp, stride, padding, dilation,
            tuple(weight.shape[2:]), 1)
    return _conv1d_transpose(x, weight, bias, stride, padding,
                             output_padding, dilation, groups, channel_last)


@def_op("conv3d_transpose")
def _conv3d_transpose(x, weight, bias, stride, padding, output_padding,
                      dilation, groups, channel_last):
    return _conv_transpose_nd(x, weight, bias, stride, padding,
                              output_padding, dilation, groups,
                              channel_last, 3)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    channel_last = data_format == "NDHWC"
    if output_size is not None:
        in_sp = tuple(x.shape[1:4]) if channel_last else tuple(x.shape[2:5])
        output_padding = opad_from_output_size(
            output_size, in_sp, stride, padding, dilation,
            tuple(weight.shape[2:]), 3)
    return _conv3d_transpose(x, weight, bias, stride, padding,
                             output_padding, dilation, groups, channel_last)
