"""Parameter initializers.

Capability parity: python/paddle/nn/initializer/ in the reference (Constant,
Normal, TruncatedNormal, Uniform, Xavier*, Kaiming*, Assign, Orthogonal,
Dirac, calculate_gain).
"""
from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp

from ...framework import random as _random
from ...framework import dtype as dtypes


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        key = _random.split_key()
        return (jax.random.normal(key, shape, jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def __call__(self, shape, dtype):
        key = _random.split_key()
        return (jax.random.truncated_normal(key, self.a, self.b, shape,
                                            jnp.float32) * self.std
                + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        key = _random.split_key()
        return jax.random.uniform(key, shape, jnp.float32, self.low,
                                  self.high).astype(dtype)


def _fans(shape):
    if len(shape) < 2:
        return shape[0] if shape else 1, shape[0] if shape else 1
    receptive = int(np.prod(shape[2:])) if len(shape) > 2 else 1
    # paddle convention: fc weights are [in, out]; convs are [out, in, k, k]
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    else:
        fan_out = shape[0] * receptive
        fan_in = shape[1] * receptive
    return fan_in, fan_out


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        key = _random.split_key()
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        key = _random.split_key()
        return jax.random.uniform(key, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        key = _random.split_key()
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        key = _random.split_key()
        return jax.random.uniform(key, shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        arr = np.asarray(self.value.numpy() if hasattr(self.value, "numpy")
                         else self.value)
        return jnp.asarray(arr.reshape(shape)).astype(dtype)


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype):
        key = _random.split_key()
        rows = shape[0]
        cols = int(np.prod(shape[1:]))
        flat = jax.random.normal(key, (max(rows, cols), min(rows, cols)),
                                 jnp.float32)
        q, r = jnp.linalg.qr(flat)
        q = q * jnp.sign(jnp.diag(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        mid = tuple(s // 2 for s in shape[2:])
        per = oc // self.groups
        for g in range(self.groups):
            for i in range(min(per, ic)):
                out[(g * per + i, i) + mid] = 1.0
        return jnp.asarray(out).astype(dtype)


def calculate_gain(nonlinearity, param=None):
    if nonlinearity in ("sigmoid", "linear", "conv1d", "conv2d", "conv3d"):
        return 1.0
    if nonlinearity == "tanh":
        return 5.0 / 3.0
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a ** 2))
    if nonlinearity == "selu":
        return 3.0 / 4.0
    return 1.0


def _to_initializer(obj):
    if isinstance(obj, Initializer):
        return obj
    if isinstance(obj, (int, float)):
        return Constant(float(obj))
    return obj


def set_global_initializer(weight_init, bias_init=None):
    # accepted for API parity; per-layer initializers take precedence
    global _GLOBAL_WEIGHT_INIT, _GLOBAL_BIAS_INIT
    _GLOBAL_WEIGHT_INIT = weight_init
    _GLOBAL_BIAS_INIT = bias_init


_GLOBAL_WEIGHT_INIT = None
_GLOBAL_BIAS_INIT = None


class Bilinear(Initializer):
    """reference: nn/initializer/Bilinear — transposed-conv weights that
    perform bilinear upsampling (weight shape [C_in, C_out, k, k] or
    [C_out, C_in, k, k]; each spatial kernel is the bilinear interpolation
    stencil)."""

    def __call__(self, shape, dtype):
        import numpy as np
        if len(shape) < 3:
            raise ValueError(
                f"Bilinear initializer needs a conv weight (>=3 dims), "
                f"got shape {shape}")
        spatial = shape[2:]
        kernels = []
        for k in spatial:
            f = (k + 1) // 2
            c = f - 1.0 if k % 2 == 1 else f - 0.5
            kernels.append(1 - np.abs(np.arange(k) - c) / f)
        stencil = kernels[0]
        for kern in kernels[1:]:
            stencil = np.multiply.outer(stencil, kern)
        w = np.zeros(shape, np.float32)
        w[...] = stencil            # same stencil per channel pair
        import jax.numpy as jnp
        return jnp.asarray(w, dtype)
