"""Layer library."""
