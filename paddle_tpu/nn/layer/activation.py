"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F
from ..initializer import Constant


def _simple(name, fn_name=None, **defaults):
    fn = getattr(F, fn_name or name.lower())

    class _Act(Layer):
        def __init__(self, *args, **kwargs):
            super().__init__()
            merged = dict(defaults)
            keys = list(defaults)
            for i, a in enumerate(args):
                merged[keys[i]] = a
            for k, v in kwargs.items():
                if k in merged:
                    merged[k] = v
            self._kwargs = merged

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


CELU = _simple("CELU", "celu", alpha=1.0)
ELU = _simple("ELU", "elu", alpha=1.0)
GELU = _simple("GELU", "gelu", approximate=False)
Hardshrink = _simple("Hardshrink", "hardshrink", threshold=0.5)
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Hardswish = _simple("Hardswish", "hardswish")
Hardtanh = _simple("Hardtanh", "hardtanh", min=-1.0, max=1.0)
LeakyReLU = _simple("LeakyReLU", "leaky_relu", negative_slope=0.01)
LogSigmoid = _simple("LogSigmoid", "sigmoid")  # fixed below
Maxout = _simple("Maxout", "maxout", groups=2, axis=1)
Mish = _simple("Mish", "mish")
ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
SELU = _simple("SELU", "selu")
Sigmoid = _simple("Sigmoid", "sigmoid")
Silu = _simple("Silu", "silu")
Softshrink = _simple("Softshrink", "softshrink", threshold=0.5)
Softsign = _simple("Softsign", "softsign")
Softplus = _simple("Softplus", "softplus", beta=1.0, threshold=20.0)
Swish = _simple("Swish", "swish")
Tanh = _simple("Tanh", "tanh")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
ThresholdedReLU = _simple("ThresholdedReLU", "relu")  # fixed below
GLU = _simple("GLU", "glu", axis=-1)


class LogSigmoid(Layer):  # noqa: F811
    def forward(self, x):
        from ... import tensor as T
        return T.log(F.sigmoid(x))


class ThresholdedReLU(Layer):  # noqa: F811
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        from ...framework.dispatch import call_op
        import jax.numpy as jnp
        thr = self.threshold
        return call_op("thresholded_relu",
                       lambda a: jnp.where(a > thr, a, 0.0), (x,), {})


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self.data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=weight_attr,
            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self.data_format)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return F.log_softmax(x, self.axis)


class RReLU(Layer):
    """reference: paddle.nn.RReLU — randomized leaky slope in train, the
    mean slope in eval."""

    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, training=self.training)


class Softmax2D(Layer):
    """reference: paddle.nn.Softmax2D — softmax over C for NCHW inputs."""

    def forward(self, x):
        return F.softmax(x, axis=-3)
