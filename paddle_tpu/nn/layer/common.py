"""Common layers: Linear, Embedding, Dropout, padding, upsampling.

Capability parity: python/paddle/nn/layer/common.py in the reference.
"""
from __future__ import annotations

import math

from .layers import Layer, ParamAttr
from .. import functional as F
from ..initializer import XavierNormal, Normal, Constant, Uniform
from ...framework import dtype as dtypes


class Linear(Layer):
    """reference: paddle.nn.Linear — weight layout [in_features, out_features]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=XavierNormal())
        self.bias = self.create_parameter(
            (out_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        q = getattr(self, "_serving_quant", None)
        if q is not None:
            # quantized-serving trace (ISSUE 9): the paged decoder
            # swapped an int8 weight into this layer and carries the
            # per-out-channel scale as a traced value in q — only ever
            # set inside its compiled programs, cleared on exit
            from ...ops.pallas.quant_matmul import quant_linear_forward
            out = quant_linear_forward(self, x, q)
        else:
            out = F.linear(x, self.weight, self.bias)
        r = getattr(self, "_tp_reduce", None)
        if r is not None:
            # tensor-parallel serving trace (ISSUE 20): this layer is a
            # row-parallel projection inside a shard_map program — its
            # matmul produced one shard's PARTIAL sum, and r is the
            # mesh all-reduce that closes the block.  Armed only during
            # the paged decoder's program traces (bias-free layers by
            # construction: a per-shard bias would be summed tp times),
            # cleared on exit like _serving_quant.
            from ...framework.tensor import wrap_array
            out = wrap_array(r(out._data))
        return out

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """reference: paddle.nn.Embedding."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.padding_idx = padding_idx
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=XavierNormal())
        if padding_idx is not None:
            self.weight._data = self.weight._data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self.padding_idx)

    def extra_repr(self):
        return f"{self.num_embeddings}, {self.embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training,
                           data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ...tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis, self.shape = axis, shape

    def forward(self, x):
        from ...tensor.manipulation import reshape
        new = list(x.shape)
        new[self.axis:self.axis + 1] = list(self.shape)
        return reshape(x, new)


class Pad1D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, self.mode, self.value, self.data_format)


class ZeroPad2D(Pad2D):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners
        self.align_mode, self.data_format = align_mode, data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode,
                             self.data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.upscale_factor, self.data_format)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.downscale_factor = downscale_factor
        self.data_format = data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.downscale_factor, self.data_format)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features), attr=weight_attr,
            default_initializer=Uniform(-1 / math.sqrt(in1_features),
                                        1 / math.sqrt(in1_features)))
        self.bias = self.create_parameter((out_features,), attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class Fold(Layer):
    """reference: paddle.nn.Fold (col2im, the transpose of Unfold)."""

    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings,
                     dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class MaxUnPool2D(Layer):
    """reference: paddle.nn.MaxUnPool2D."""

    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCHW",
                 output_size=None, name=None):
        super().__init__()
        self.kernel_size = kernel_size
        self.stride = stride
        self.padding = padding
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool2d(x, indices, self.kernel_size, self.stride,
                              self.padding, self.output_size)


class ChannelShuffle(Layer):
    """reference: paddle.nn.ChannelShuffle."""

    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.groups = groups
        self.data_format = data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.groups, self.data_format)


class SpectralNorm(Layer):
    """reference: paddle.nn.SpectralNorm (spectral_norm op) — normalizes a
    weight by its largest singular value, estimated by power iteration with
    persistent u/v buffers."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype="float32"):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        h = int(weight_shape[dim])
        w = 1
        for i, s in enumerate(weight_shape):
            if i != dim:
                w *= int(s)
        import numpy as _np
        from ...framework.tensor import to_tensor as _tt
        rng = _np.random.default_rng(0)
        u = rng.standard_normal(h).astype(dtype)
        v = rng.standard_normal(w).astype(dtype)
        self.register_buffer("weight_u", _tt(u / _np.linalg.norm(u)))
        self.register_buffer("weight_v", _tt(v / _np.linalg.norm(v)))

    def forward(self, weight):
        import jax.numpy as _jnp
        from ...framework.dispatch import call_op

        dim, iters, eps = self.dim, self.power_iters, self.eps

        def _fn(w, u, v):
            mat = _jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            for _ in range(iters):
                v = mat.T @ u
                v = v / (_jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (_jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma, u, v

        out, u, v = call_op("spectral_norm", _fn,
                            (weight, self.weight_u, self.weight_v), {})
        # persistent power-iteration state (paddle semantics) — but never
        # leak tracers into the buffers when compiled (to_static/TrainStep)
        import jax as _jax
        if not isinstance(u._data, _jax.core.Tracer):
            self.weight_u._data = u._data
            self.weight_v._data = v._data
        return out


class ZeroPad1D(Pad1D):
    def __init__(self, padding, data_format="NCL", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class ZeroPad3D(Pad3D):
    def __init__(self, padding, data_format="NCDHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.args = (p, epsilon, keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, *self.args)


class FeatureAlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.feature_alpha_dropout(x, self.p, self.training)
