"""Conv + pooling layers.

Capability parity: python/paddle/nn/layer/conv.py + pooling.py in the
reference.  Weight layout matches the reference: [out_ch, in_ch/groups, *k]
for conv, [in_ch, out_ch/groups, *k] for transpose conv.
"""
from __future__ import annotations

import math

import numpy as np

from .layers import Layer
from .. import functional as F
from ..initializer import KaimingUniform, Uniform


def _ntuple(v, n):
    return (int(v),) * n if isinstance(v, (int, np.integer)) else tuple(v)


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, weight_attr, bias_attr, data_format, ndim,
                 transpose=False, output_padding=0):
        super().__init__()
        self.in_channels = in_channels
        self.out_channels = out_channels
        self.kernel_size = _ntuple(kernel_size, ndim)
        self.stride = stride
        self.padding = padding
        self.dilation = dilation
        self.groups = groups
        self.data_format = data_format
        self.output_padding = output_padding
        self._transpose = transpose
        if transpose:
            shape = (in_channels, out_channels // groups) + self.kernel_size
        else:
            shape = (out_channels, in_channels // groups) + self.kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self.kernel_size))
        self.weight = self.create_parameter(
            shape, attr=weight_attr,
            default_initializer=KaimingUniform(fan_in=fan_in,
                                               nonlinearity="leaky_relu",
                                               negative_slope=math.sqrt(5)))
        bound = 1 / math.sqrt(fan_in)
        self.bias = self.create_parameter(
            (out_channels,), attr=bias_attr, is_bias=True,
            default_initializer=Uniform(-bound, bound))

    def extra_repr(self):
        return (f"{self.in_channels}, {self.out_channels}, "
                f"kernel_size={self.kernel_size}, stride={self.stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2D(_ConvNd):
    """reference: paddle.nn.Conv2D (nn/layer/conv.py)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self.stride, self.padding,
                        self.dilation, self.groups, self.data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation,
                                  self.data_format, output_size)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.max_pool1d(x, *self.args, ceil_mode=self.ceil_mode)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool2d(x, *self.args, ceil_mode=self.ceil_mode,
                            data_format=self.data_format)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.exclusive = exclusive
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.avg_pool1d(x, *self.args, exclusive=self.exclusive,
                            ceil_mode=self.ceil_mode)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.exclusive = exclusive
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool2d(x, *self.args, ceil_mode=self.ceil_mode,
                            exclusive=self.exclusive,
                            data_format=self.data_format)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool1d(x, self.output_size)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self.output_size, self.data_format)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self.output_size)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 1, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self.stride,
                                  self.padding, self.output_padding,
                                  self.groups, self.dilation, output_size,
                                  self.data_format)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.return_mask = return_mask
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, *self.args, ceil_mode=self.ceil_mode,
                            return_mask=self.return_mask,
                            data_format=self.data_format)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.ceil_mode = ceil_mode
        self.exclusive = exclusive
        self.divisor_override = divisor_override
        self.data_format = data_format

    def forward(self, x):
        return F.avg_pool3d(x, *self.args, ceil_mode=self.ceil_mode,
                            exclusive=self.exclusive,
                            divisor_override=self.divisor_override,
                            data_format=self.data_format)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size, self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size, self.return_mask)


class LPPool1D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCL", name=None):
        super().__init__()
        self.args = (float(norm_type), kernel_size, stride, padding)
        self.ceil_mode = ceil_mode
        self.data_format = data_format

    def forward(self, x):
        return F.lp_pool1d(x, *self.args, ceil_mode=self.ceil_mode,
                           data_format=self.data_format)


class LPPool2D(Layer):
    def __init__(self, norm_type, kernel_size, stride=None, padding=0,
                 ceil_mode=False, data_format="NCHW", name=None):
        super().__init__()
        self.args = (float(norm_type), kernel_size, stride, padding)
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return F.lp_pool2d(x, *self.args, self.ceil_mode)


class FractionalMaxPool2D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool2d(x, *self.args)


class FractionalMaxPool3D(Layer):
    def __init__(self, output_size, kernel_size=None, random_u=None,
                 return_mask=False, name=None):
        super().__init__()
        self.args = (output_size, kernel_size, random_u, return_mask)

    def forward(self, x):
        return F.fractional_max_pool3d(x, *self.args)


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, data_format="NCL",
                 output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool1d(x, indices, *self.args,
                              output_size=self.output_size)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding)
        self.output_size = output_size

    def forward(self, x, indices):
        return F.max_unpool3d(x, indices, *self.args,
                              output_size=self.output_size)
