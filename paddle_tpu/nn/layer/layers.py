"""Layer base class + containers.

Capability parity: python/paddle/nn/layer/layers.py (Layer, ~reference
layer/layers.py Layer class) and containers.py (Sequential/LayerList/
LayerDict/ParameterList).

TPU-native: parameters are framework Parameters (jax.Array payloads); the
whole Layer functionalizes cleanly for jit via state_dict <-> pytree helpers
(used by paddle_tpu.jit.to_static and the distributed wrappers).
"""
from __future__ import annotations

import collections
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor, Parameter
from ...framework import dtype as dtypes
from ...framework.tape import no_grad
from ..initializer import Constant, XavierNormal, Normal, _to_initializer


class ParamAttr:
    """reference: python/paddle/base/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if attr is False:
            return False
        return ParamAttr(initializer=_to_initializer(attr))


class Layer:
    """Base building block (reference: paddle.nn.Layer)."""

    def __init__(self, name_scope: Optional[str] = None, dtype="float32"):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype) if dtype else None
        self._parameters: Dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: Dict[str, "Layer"] = collections.OrderedDict()
        self._buffers: Dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._name_scope = name_scope or type(self).__name__.lower()
        self._init_in_dynamic_mode = True

    # ------------------------------------------------------------ attr mgmt
    def __setattr__(self, name: str, value: Any) -> None:
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning params")
            for d in (layers, buffers):
                if d is not None:
                    d.pop(name, None)
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            for d in (params, buffers):
                if d is not None:
                    d.pop(name, None)
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params.pop(name)
                    object.__setattr__(self, name, None)
                    return
                if isinstance(value, Tensor):
                    params[name].set_value(value)
                    return
            if layers is not None and name in layers and value is None:
                layers.pop(name)
                object.__setattr__(self, name, None)
                return
            if buffers is not None and name in buffers:
                if value is None:
                    buffers.pop(name)
                    object.__setattr__(self, name, None)
                    return
                if isinstance(value, Tensor):
                    buffers[name] = value
                    return
            object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_sub_layers", "_buffers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._sub_layers) + list(self._buffers)

    # ----------------------------------------------------------- factories
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None) -> Parameter:
        """reference: Layer.create_parameter (layers.py)."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype else (
            self._dtype or dtypes.get_default_dtype())
        init = attr.initializer or default_initializer
        if init is None:
            init = Constant(0.0) if is_bias else XavierNormal()
        data = init(tuple(int(s) for s in shape), dtype)
        p = Parameter(data, trainable=attr.trainable, name=attr.name)
        p.optimize_attr["learning_rate"] = attr.learning_rate
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        t = Tensor(np.zeros([0], dtype="float32"), dtype=dtype)
        t.name = name or ""
        return t

    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is None:
            self._parameters[name] = None
        else:
            self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    # ----------------------------------------------------------- iteration
    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True,
                         remove_duplicate=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for pname, p in layer._parameters.items():
                if p is None or (remove_duplicate and id(p) in seen):
                    continue
                seen.add(id(p))
                yield (name + ("." if name else "") + pname, p)

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self._traverse(prefix, include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (name + ("." if name else "") + bname, b)

    def _traverse(self, prefix="", include_sublayers=True):
        yield (prefix, self)
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + ("." if prefix else "") + lname
                yield from layer._traverse(sub_prefix, True)

    def children(self) -> Iterator["Layer"]:
        for _, layer in self.named_children():
            yield layer

    def named_children(self):
        for name, layer in self._sub_layers.items():
            if layer is not None:
                yield name, layer

    def sublayers(self, include_self=False) -> List["Layer"]:
        out = []
        for name, layer in self._traverse("", True):
            if name == "" and not include_self:
                continue
            out.append(layer)
        return out

    def named_sublayers(self, prefix="", include_self=False):
        for name, layer in self._traverse(prefix, True):
            if name == prefix and not include_self:
                continue
            yield name, layer

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for layer in self.children():
            layer.apply(fn)
        fn(self)
        return self

    # ----------------------------------------------------------- train/eval
    def train(self):
        self.training = True
        for layer in self.sublayers():
            layer.training = True
        return self

    def eval(self):
        self.training = False
        for layer in self.sublayers():
            layer.training = False
        return self

    # ----------------------------------------------------------- state dict
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip("."),
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for name, layer in self._traverse(structured_name_prefix.rstrip("."),
                                          include_sublayers):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[name + ("." if name else "") + bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """reference: Layer.set_state_dict / set_dict."""
        own = self.state_dict()
        missing, unexpected = [], []
        for name, target in own.items():
            if name in state_dict:
                value = state_dict[name]
                arr = value._data if isinstance(value, Tensor) else jnp.asarray(
                    np.asarray(value))
                if tuple(arr.shape) != tuple(target._data.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: loaded {arr.shape} vs "
                        f"{tuple(target._data.shape)}")
                target._data = arr.astype(target._data.dtype)
            else:
                missing.append(name)
        for name in state_dict:
            if name not in own:
                unexpected.append(name)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # ----------------------------------------------------------------- call
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, inputs, outputs)
            if res is not None:
                outputs = res
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # ------------------------------------------------------------ dtype/dev
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            d = dtypes.convert_dtype(dtype)
            with no_grad():
                for p in self.parameters():
                    if dtypes.is_floating_point(p.dtype):
                        p._data = p._data.astype(d)
                for b in self.buffers():
                    if dtypes.is_floating_point(b.dtype):
                        b._data = b._data.astype(d)
            self._dtype = d
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def float(self):
        return self.to(dtype="float32")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    def half(self):
        return self.to(dtype="float16")

    def clear_gradients(self, set_to_zero=False):
        for p in self.parameters():
            p.clear_gradient(set_to_zero)

    def full_name(self):
        return self._name_scope

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            child = repr(layer).split("\n")
            child = [child[0]] + ["  " + c for c in child[1:]]
            lines.append(f"  ({name}): " + "\n".join(child))
        main = f"{type(self).__name__}({extra}"
        if lines:
            return main + "\n" + "\n".join(lines) + "\n)"
        return main + ")"


class _HookHandle:
    _next_id = 0

    def __init__(self, store):
        self._store = store
        self.id = _HookHandle._next_id
        _HookHandle._next_id += 1

    def remove(self):
        self._store.pop(self.id, None)


class Sequential(Layer):
    """reference: paddle.nn.Sequential."""

    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                len(layers[0]) and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                if isinstance(layer, tuple):
                    self.add_sublayer(layer[0], layer[1])
                else:
                    self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        keys = list(self._sub_layers)
        return self._sub_layers[keys[idx]]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x


class LayerList(Layer):
    """reference: paddle.nn.LayerList."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, layer in enumerate(sublayers):
                self.add_sublayer(str(i), layer)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return self._sub_layers[str(self._abs_idx(idx))]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(self._abs_idx(idx))] = layer

    def __delitem__(self, idx):
        del self._sub_layers[str(self._abs_idx(idx))]
        layers = list(self._sub_layers.values())
        self._sub_layers.clear()
        for i, layer in enumerate(layers):
            self._sub_layers[str(i)] = layer

    def _abs_idx(self, idx):
        return idx if idx >= 0 else len(self) + idx

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())

    def append(self, layer):
        self.add_sublayer(str(len(self)), layer)
        return self

    def insert(self, index, layer):
        layers = list(self._sub_layers.values())
        layers.insert(index, layer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, layers):
        for layer in layers:
            self.append(layer)
        return self


class LayerDict(Layer):
    """reference: paddle.nn.LayerDict."""

    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def __contains__(self, key):
        return key in self._sub_layers

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()

    def update(self, sublayers):
        if isinstance(sublayers, dict):
            sublayers = sublayers.items()
        for key, layer in sublayers:
            self.add_sublayer(key, layer)

    def clear(self):
        self._sub_layers.clear()

    def pop(self, key):
        layer = self._sub_layers.pop(key)
        return layer


class ParameterList(Layer):
    """reference: paddle.nn.ParameterList."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __setitem__(self, idx, p):
        self._parameters[str(idx)] = p

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())

    def append(self, p):
        self.add_parameter(str(len(self)), p)
        return self


class ParameterDict(Layer):
    """reference: paddle.nn.ParameterDict."""

    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            self.update(parameters)

    def __getitem__(self, key):
        return self._parameters[key]

    def __setitem__(self, key, p):
        self.add_parameter(key, p)

    def __delitem__(self, key):
        del self._parameters[key]

    def __contains__(self, key):
        return key in self._parameters

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters)

    def keys(self):
        return self._parameters.keys()

    def values(self):
        return self._parameters.values()

    def items(self):
        return self._parameters.items()

    def update(self, parameters):
        items = parameters.items() if hasattr(parameters, "items") \
            else parameters
        for k, p in items:
            self.add_parameter(k, p)
        return self


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x
