"""Loss layers (reference: python/paddle/nn/layer/loss.py)."""
from __future__ import annotations

from .layers import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    """reference: paddle.nn.CrossEntropyLoss."""

    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True,
                 label_smoothing=0.0, name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self.weight,
                               ignore_index=self.ignore_index,
                               reduction=self.reduction,
                               soft_label=self.soft_label, axis=self.axis,
                               use_softmax=self.use_softmax,
                               label_smoothing=self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index,
                          self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight,
                                      self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None,
                 name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(
            logit, label, self.weight, self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin,
                                     self.reduction)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin,
                                      self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class CTCLoss(Layer):
    """reference: paddle.nn.CTCLoss (nn/layer/loss.py:1275, warpctc-backed
    there; lax.scan forward-backward here — see functional/ctc.py)."""

    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          self.blank, self.reduction, norm_by_times)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.args = (full, epsilon, reduction)

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, *self.args)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (p, margin, weight, reduction)

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, *self.args)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive, negative,
                                                   *self.args)


class HSigmoidLoss(Layer):
    """reference: paddle.nn.HSigmoidLoss — hierarchical sigmoid over a
    user-supplied code tree (path_table/path_code as in the reference's
    custom-tree mode; see functional/extra.py hsigmoid_loss)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False, name=None):
        super().__init__()
        self.num_classes = num_classes
        # one weight/bias row per internal tree node
        self.weight = self.create_parameter(
            (num_classes - 1, feature_size), attr=weight_attr)
        self.bias = self.create_parameter(
            (num_classes - 1,), attr=bias_attr, is_bias=True)

    def forward(self, input, label, path_table=None, path_code=None):
        if path_table is None or path_code is None:
            from ... import tensor as T
            import numpy as np
            # default complete-binary-tree paths (reference default mode)
            depth = max(1, int(np.ceil(np.log2(max(self.num_classes, 2)))))
            lab = label.numpy().reshape(-1)
            tables, codes = [], []
            for c in lab:
                node, tab, code = int(c) + self.num_classes - 1, [], []
                while node > 0:
                    parent = (node - 1) // 2
                    tab.append(parent)
                    code.append(node % 2)   # 1 = left child? fixed convention
                    node = parent
                tab = tab[::-1][:depth] + [-1] * max(0, depth - len(tab))
                code = code[::-1][:depth] + [0] * max(0, depth - len(code))
                tables.append(tab[:depth])
                codes.append(code[:depth])
            path_table = T.to_tensor(np.array(tables, np.int32))
            path_code = T.to_tensor(np.array(codes, np.int32))
        return F.hsigmoid_loss(input, label, self.weight, self.bias,
                               path_table, path_code)


class RNNTLoss(Layer):
    def __init__(self, blank=0, fastemit_lambda=0.001, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (blank, fastemit_lambda, reduction)

    def forward(self, input, label, input_lengths, label_lengths):
        return F.rnnt_loss(input, label, input_lengths, label_lengths,
                           *self.args)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference: paddle.nn.AdaptiveLogSoftmaxWithLoss (Grave et al.) —
    head over [shortlist + clusters], factorized per-cluster tails with
    dims divided by div_value**k."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        self.cutoffs = list(cutoffs) + [n_classes]
        self.n_clusters = len(self.cutoffs) - 1
        shortlist = self.cutoffs[0]
        self.head_weight = self.create_parameter(
            (in_features, shortlist + self.n_clusters))
        self.head_bias = self.create_parameter(
            (shortlist + self.n_clusters,), is_bias=True) if head_bias \
            else None
        self.tail_weights = []
        for k in range(self.n_clusters):
            hsz = max(1, int(in_features // (div_value ** (k + 1))))
            osz = self.cutoffs[k + 1] - self.cutoffs[k]
            proj = self.create_parameter((in_features, hsz))
            out = self.create_parameter((hsz, osz))
            setattr(self, f"_tail_{k}_proj", proj)
            setattr(self, f"_tail_{k}_out", out)
            self.tail_weights.append([proj, out])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights, self.cutoffs,
            self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-probability table."""
        import jax.numpy as jnp
        from ...framework.dispatch import call_op

        def _fn(x, head_w, head_b, *tails):
            head = x @ head_w
            if head_b is not None:
                head = head + head_b
            head_lp = jax.nn.log_softmax(head, axis=-1)
            shortlist = self.cutoffs[0]
            parts = [head_lp[:, :shortlist]]
            for k in range(self.n_clusters):
                proj, out = tails[2 * k], tails[2 * k + 1]
                tail_lp = jax.nn.log_softmax((x @ proj) @ out, axis=-1)
                parts.append(head_lp[:, shortlist + k:shortlist + k + 1]
                             + tail_lp)
            return jnp.concatenate(parts, axis=-1)

        flat_tails = [w for pair in self.tail_weights for w in pair]
        import jax
        return call_op("adaptive_log_prob", _fn,
                       (input, self.head_weight, self.head_bias,
                        *flat_tails), {})

    def predict(self, input):
        from ... import tensor as T
        return T.argmax(self.log_prob(input), axis=-1)
