"""Normalization layers.

Capability parity: python/paddle/nn/layer/norm.py in the reference
(BatchNorm1D/2D/3D, LayerNorm, GroupNorm, InstanceNorm, SyncBatchNorm,
SpectralNorm omitted round-1) + RMSNorm (incubate in the reference; first-
class here since it is the LLM-default norm).
"""
from __future__ import annotations

import numpy as np

from .layers import Layer
from .. import functional as F
from ..initializer import Constant
from ...framework.tensor import Tensor
from ...framework.tape import no_grad


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                          is_bias=True)
        from ...tensor.creation import zeros, ones
        self.register_buffer("_mean", zeros([num_features]))
        self.register_buffer("_variance", ones([num_features]))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self.momentum, epsilon=self.epsilon,
                            data_format=self.data_format,
                            use_global_stats=self.use_global_stats)

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}"


class BatchNorm(_BatchNormBase):
    pass


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCHW" if data_format == "NCL" else "NHWC",
                         use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NHWC",
                         use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU under SPMD, batch stats are computed over the *global* batch by
    XLA when the batch axis is sharded — sync-BN falls out of GSPMD for the
    jit path (reference needs a dedicated CUDA kernel + comm:
    nn/layer/norm.py SyncBatchNorm)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            with no_grad():
                out.weight.copy_(layer.weight)
                out.bias.copy_(layer.bias)
                out._mean.copy_(layer._mean)
                out._variance.copy_(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            out.add_sublayer(name, cls.convert_sync_batchnorm(sub))
        return out


class LayerNorm(Layer):
    """reference: paddle.nn.LayerNorm."""

    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, (int, np.integer)):
            normalized_shape = [int(normalized_shape)]
        self.normalized_shape = list(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr,
                default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias,
                            self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}"


class RMSNorm(Layer):
    """RMS norm (reference exposes fused rms_norm via incubate
    paddle/phi/kernels/fusion; first-class layer here)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.epsilon = epsilon
        self.weight = self.create_parameter(
            (hidden_size,), attr=weight_attr,
            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=weight_attr,
            default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.epsilon, self.weight,
                            self.bias, self.data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr,
                default_initializer=Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)
