"""Recurrent layers: SimpleRNN / LSTM / GRU.

Capability parity: python/paddle/nn/layer/rnn.py in the reference.

TPU-native: the time loop is ``lax.scan`` (compiles to a single fused XLA
while-loop; no per-step dispatch), matmuls batched over the gate dimension.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

from .layers import Layer
from ...framework.dispatch import call_op
from ...framework.tensor import Tensor
from ..initializer import Uniform
from ... import tensor as T


def _lstm_cell(x, h, c, w_ih, w_hh, b_ih, b_hh):
    gates = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c2 = f * c + i * g
    return jnp.tanh(c2) * o, c2


def _gru_cell(x, h, w_ih, w_hh, b_ih, b_hh):
    gi = x @ w_ih.T + b_ih
    gh = h @ w_hh.T + b_hh
    ir, iz, inn = jnp.split(gi, 3, axis=-1)
    hr, hz, hn = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(ir + hr)
    z = jax.nn.sigmoid(iz + hz)
    n = jnp.tanh(inn + r * hn)
    return (1 - z) * n + z * h


def _rnn_cell(x, h, w_ih, w_hh, b_ih, b_hh, activation):
    out = x @ w_ih.T + h @ w_hh.T + b_ih + b_hh
    return jnp.tanh(out) if activation == "tanh" else jax.nn.relu(out)


class RNNBase(Layer):
    """Shared multi-layer bidirectional scan driver."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        gate_mult = {"LSTM": 4, "GRU": 3, "RNN": 1}[mode]
        std = 1.0 / math.sqrt(hidden_size)
        self._param_names = []
        for layer in range(num_layers):
            for d in range(self.bidirect):
                in_size = input_size if layer == 0 else hidden_size * self.bidirect
                suffix = f"_reverse" if d == 1 else ""
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}",
                         f"bias_hh_l{layer}{suffix}"]
                shapes = [(gate_mult * hidden_size, in_size),
                          (gate_mult * hidden_size, hidden_size),
                          (gate_mult * hidden_size,),
                          (gate_mult * hidden_size,)]
                attrs = [weight_ih_attr, weight_hh_attr, bias_ih_attr,
                         bias_hh_attr]
                for n, s, a in zip(names, shapes, attrs):
                    p = self.create_parameter(
                        s, attr=a, default_initializer=Uniform(-std, std))
                    self.add_parameter(n, p)
                self._param_names.append(names)

    def _cell_fn(self):
        mode = self.mode
        act = self.activation
        if mode == "LSTM":
            return lambda x, state, w: _lstm_cell(x, state[0], state[1], *w), 2
        if mode == "GRU":
            return lambda x, state, w: _gru_cell(x, state[0], *w), 1
        return lambda x, state, w: _rnn_cell(x, state[0], *w, act), 1

    def forward(self, inputs, initial_states=None, sequence_length=None):
        params = []
        for names in self._param_names:
            params.extend(self._parameters[n] for n in names)
        mode = self.mode
        num_layers, bidirect = self.num_layers, self.bidirect
        hidden = self.hidden_size
        time_major = self.time_major
        is_lstm = mode == "LSTM"

        def _run(x, plist, init_h, init_c):
            if not time_major:
                x = jnp.swapaxes(x, 0, 1)  # (seq, batch, feat)
            batch = x.shape[1]
            cell, _ = self._cell_fn()
            h_finals, c_finals = [], []
            layer_in = x
            idx = 0
            for layer in range(num_layers):
                outs = []
                for d in range(bidirect):
                    w = plist[idx * 4:(idx + 1) * 4]
                    idx += 1
                    gi = layer * bidirect + d
                    h0 = init_h[gi]
                    c0 = init_c[gi] if is_lstm else None
                    seq = jnp.flip(layer_in, 0) if d == 1 else layer_in

                    def step(carry, xt):
                        if is_lstm:
                            h, c = cell(xt, carry, w)
                            return (h, c), h
                        h = cell(xt, carry, w)
                        return (h,), h
                    carry0 = (h0, c0) if is_lstm else (h0,)
                    carry, ys = lax.scan(step, carry0, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs.append(ys)
                    h_finals.append(carry[0])
                    if is_lstm:
                        c_finals.append(carry[1])
                layer_in = jnp.concatenate(outs, axis=-1) if bidirect == 2 \
                    else outs[0]
            out = layer_in if time_major else jnp.swapaxes(layer_in, 0, 1)
            h_stack = jnp.stack(h_finals)
            if is_lstm:
                return out, h_stack, jnp.stack(c_finals)
            return out, h_stack

        batch = inputs.shape[0] if not time_major else inputs.shape[1]
        n_states = num_layers * bidirect
        if initial_states is None:
            zeros = T.zeros([n_states, batch, hidden], dtype=inputs.dtype)
            init_h, init_c = zeros, zeros
        elif is_lstm:
            init_h, init_c = initial_states
        else:
            init_h, init_c = initial_states, None
        if init_c is None:
            init_c = T.zeros([n_states, batch, hidden], dtype=inputs.dtype)

        res = call_op(f"rnn_{mode}", _run, (inputs, params, init_h, init_c), {})
        if is_lstm:
            out, h, c = res
            return out, (h, c)
        out, h = res
        return out, h


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", **kwargs):
        super().__init__("RNN", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, activation, **kwargs)


class LSTM(RNNBase):
    """reference: paddle.nn.LSTM."""

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        kwargs.pop("activation", None)
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, **kwargs):
        kwargs.pop("activation", None)
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, **kwargs)


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size), attr=weight_ih_attr,
            default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, hidden_size), attr=weight_hh_attr,
            default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter((4 * hidden_size,),
                                             attr=bias_ih_attr, is_bias=True)
        self.bias_hh = self.create_parameter((4 * hidden_size,),
                                             attr=bias_hh_attr, is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            states = (T.zeros([inputs.shape[0], self.hidden_size]),) * 2
        h, c = states
        out = call_op("lstm_cell", lambda x, h, c, wi, wh, bi, bh:
                      _lstm_cell(x, h, c, wi, wh, bi, bh),
                      (inputs, h, c, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh), {})
        return out[0], out


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size),
            default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size),
            default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter((3 * hidden_size,), is_bias=True)
        self.bias_hh = self.create_parameter((3 * hidden_size,), is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            states = T.zeros([inputs.shape[0], self.hidden_size])
        out = call_op("gru_cell", lambda x, h, wi, wh, bi, bh:
                      _gru_cell(x, h, wi, wh, bi, bh),
                      (inputs, states, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh), {})
        return out, out


class SimpleRNNCell(Layer):
    def __init__(self, input_size, hidden_size, activation="tanh", **kwargs):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), default_initializer=Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), default_initializer=Uniform(-std, std))
        self.bias_ih = self.create_parameter((hidden_size,), is_bias=True)
        self.bias_hh = self.create_parameter((hidden_size,), is_bias=True)

    def forward(self, inputs, states=None):
        if states is None:
            states = T.zeros([inputs.shape[0], self.hidden_size])
        act = self.activation
        out = call_op("rnn_cell", lambda x, h, wi, wh, bi, bh:
                      _rnn_cell(x, h, wi, wh, bi, bh, act),
                      (inputs, states, self.weight_ih, self.weight_hh,
                       self.bias_ih, self.bias_hh), {})
        return out, out


class RNNCellBase(Layer):
    """reference: paddle.nn.RNNCellBase — base for user cells consumed by
    RNN/BiRNN; provides zero initial states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        if shape is None:
            shape = (self.hidden_size,)
        full = (batch,) + tuple(shape)
        out = T.full(full, init_value, dtype or "float32")
        return out


class RNN(Layer):
    """reference: paddle.nn.RNN (layer/rnn.py) — run a cell over the time
    axis.  The step loop is a static Python loop (T is a trace-time
    constant), so under ``to_static`` the whole unrolled sweep compiles
    into one XLA program."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        time_axis = 0 if self.time_major else 1
        steps = inputs.shape[time_axis]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        states = initial_states
        outs = [None] * steps
        for t in order:
            x_t = inputs[t] if self.time_major else inputs[:, t]
            out, new_states = self.cell(x_t, states, **kwargs)
            if sequence_length is not None:
                keep = (T.to_tensor(t) < sequence_length).astype(out.dtype)
                mask = keep.reshape([-1] + [1] * (out.ndim - 1))
                out = out * mask
                # before the first step the implicit initial state is zeros;
                # padded timesteps must carry it, not the cell's garbage
                # (matters for is_reverse, which starts in the padding)
                prev = states if states is not None else \
                    _zeros_like_states(new_states)
                new_states = _mask_states(new_states, prev, mask)
            states = new_states
            outs[t] = out
        outputs = T.stack(outs, axis=time_axis)
        return outputs, states


def _mask_states(new, old, mask):
    if isinstance(new, (tuple, list)):
        return type(new)(_mask_states(n, o, mask) for n, o in zip(new, old))
    return new * mask + old * (1 - mask)


def _zeros_like_states(s):
    if isinstance(s, (tuple, list)):
        return type(s)(_zeros_like_states(x) for x in s)
    return s * 0.0


class BiRNN(Layer):
    """reference: paddle.nn.BiRNN — forward + backward cells, outputs
    concatenated on the feature axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            states_fw = states_bw = None
        else:
            states_fw, states_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw, sequence_length,
                                    **kwargs)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw, sequence_length,
                                    **kwargs)
        return T.concat([out_fw, out_bw], axis=-1), (st_fw, st_bw)
