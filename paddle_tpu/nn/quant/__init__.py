"""paddle_tpu.nn.quant — quantization layers + weight-quantized ops
(reference: python/paddle/nn/quant/)."""
from .format import (  # noqa: F401
    Stub, QuantizedLinear, QuantizedConv2D, quantize_weight_per_channel,
)
from .qat_layers import (  # noqa: F401
    QuantedLinear, QuantedConv2D, DEFAULT_QAT_LAYER_MAPPINGS,
)
from .quantized_linear import (  # noqa: F401
    weight_quantize, weight_dequantize, weight_only_linear, llm_int8_linear,
)

__all__ = [
    "Stub", "QuantizedLinear", "QuantizedConv2D", "QuantedLinear",
    "QuantedConv2D", "weight_quantize", "weight_dequantize",
    "weight_only_linear", "llm_int8_linear",
]
