"""Converted (inference-form) quantized layers + Stub.

Capability parity with the reference's conversion format layers
(reference: python/paddle/nn/quant/format.py — ConvertibleQuantedLayer /
LinearQuanterDequanter; stub.py — Stub observing an activation site).

The converted Linear stores an int8 weight + per-channel scales and runs the
weight-only path (dequant fused into matmul by XLA).
"""
from __future__ import annotations

import numpy as np

from ...framework.tensor import Tensor, to_tensor
from ..layer.layers import Layer
from .. import functional as F
from .quantized_linear import weight_only_linear


class Stub(Layer):
    """Marks an activation quantization site in user models; QAT replaces it
    with the configured quanter, otherwise identity (reference: stub.py)."""

    def __init__(self, observer=None):
        super().__init__()
        self._observer = observer
        self._quanter = None

    def forward(self, x):
        if self._quanter is not None:
            return self._quanter(x)
        return x


def _scale_for(weight_ndim, scale: Tensor, quant_axis):
    """Reshape a stored scale so it broadcasts against the weight along
    ``quant_axis`` (None = per-tensor scalar)."""
    if quant_axis is None or scale.ndim == 0:
        return scale
    shape = [1] * weight_ndim
    shape[quant_axis] = -1
    return scale.reshape(shape)


class QuantizedLinear(Layer):
    """Inference-form Linear: int8 weight + float scales along quant_axis."""

    def __init__(self, weight_int8: Tensor, scale: Tensor, bias,
                 act_scale=None, act_bits=8, quant_axis=1):
        super().__init__()
        self.register_buffer("weight", weight_int8)
        self.register_buffer("weight_scale", scale)
        self.bias = bias
        self.act_scale = act_scale   # exported metadata (input threshold)
        self.act_bits = act_bits
        self.quant_axis = quant_axis

    def forward(self, x):
        if self.quant_axis == 1 or self.quant_axis is None:
            return weight_only_linear(x, self.weight, self.weight_scale,
                                      self.bias)
        w = self.weight.astype(x.dtype) * _scale_for(
            2, self.weight_scale, self.quant_axis).astype(x.dtype)
        return F.linear(x, w, self.bias)


class QuantizedConv2D(Layer):
    """Inference-form Conv2D: int8 weight + scales along quant_axis; the
    dequant multiply is fused by XLA into the conv's weight load."""

    def __init__(self, weight_int8, scale, bias, conv_attrs, act_scale=None,
                 act_bits=8, quant_axis=0):
        super().__init__()
        self.register_buffer("weight", weight_int8)
        self.register_buffer("weight_scale", scale)
        self.bias = bias
        self.act_scale = act_scale
        self.act_bits = act_bits
        self.quant_axis = quant_axis
        self._attrs = conv_attrs

    def forward(self, x):
        w = self.weight.astype(x.dtype) * _scale_for(
            4, self.weight_scale, self.quant_axis).astype(x.dtype)
        a = self._attrs
        return F.conv2d(x, w, self.bias, a["stride"], a["padding"],
                        a["dilation"], a["groups"], a["data_format"])


def quantize_weight_per_channel(w: Tensor, quant_axis, bits: int = 8,
                                threshold=None):
    """Host-side weight quantization for conversion: returns
    (int8 Tensor, float32 scale Tensor along quant_axis — scalar when
    quant_axis is None).  ``threshold`` (calibrated absmax, scalar or
    per-channel) overrides the recomputed absmax so calibration choices
    (e.g. KL/Hist clipping) survive conversion."""
    arr = np.asarray(w.numpy(), dtype=np.float32)
    bnt = float((1 << (bits - 1)) - 1)
    if threshold is not None:
        absmax = np.asarray(
            threshold.numpy() if hasattr(threshold, "numpy") else threshold,
            dtype=np.float32)
    elif quant_axis is None:
        absmax = np.abs(arr).max()
    else:
        axes = tuple(i for i in range(arr.ndim) if i != quant_axis)
        absmax = np.abs(arr).max(axis=axes)
    scale = np.maximum(absmax, 1e-9) / bnt
    if quant_axis is None or np.ndim(scale) == 0:
        s = scale
    else:
        shape = [1] * arr.ndim
        shape[quant_axis] = -1
        s = scale.reshape(shape)
    q = np.clip(np.round(arr / s), -bnt, bnt).astype(np.int8)
    return to_tensor(q), to_tensor(np.asarray(scale, dtype=np.float32))
