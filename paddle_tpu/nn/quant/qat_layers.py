"""QAT wrapper layers: fake-quantized Linear / Conv2D.

Capability parity with the reference's quanted layers
(reference: python/paddle/nn/quant/qat/linear.py, conv.py — QuantedLinear /
QuantedConv2D hold the source layer's parameters and apply activation/weight
fake quanters in forward).
"""
from __future__ import annotations

from ..layer.layers import Layer
from .. import functional as F


class QuantedLinear(Layer):
    def __init__(self, layer, q_config):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._source = layer
        self.weight_quanter = None
        self.activation_quanter = None
        if q_config.weight is not None:
            self.weight_quanter = q_config.weight._instance(layer)
        if q_config.activation is not None:
            self.activation_quanter = q_config.activation._instance(layer)

    def forward(self, x):
        w = self.weight
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return F.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    def __init__(self, layer, q_config):
        super().__init__()
        self.weight = layer.weight
        self.bias = layer.bias
        self._source = layer
        self.weight_quanter = None
        self.activation_quanter = None
        if q_config.weight is not None:
            self.weight_quanter = q_config.weight._instance(layer)
        if q_config.activation is not None:
            self.activation_quanter = q_config.activation._instance(layer)

    def forward(self, x):
        w = self.weight
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        src = self._source
        return F.conv2d(x, w, self.bias, src.stride, src.padding,
                        src.dilation, src.groups, src.data_format)


def _default_mappings():
    from ..layer.common import Linear
    from ..layer.conv_pool import Conv2D
    return {Linear: QuantedLinear, Conv2D: QuantedConv2D}


DEFAULT_QAT_LAYER_MAPPINGS = _default_mappings()
