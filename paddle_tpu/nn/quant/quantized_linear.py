"""Weight-quantized inference ops: int8/int4 weight-only linear.

Capability parity with the reference's quantized linear API
(reference: python/paddle/nn/quant/quantized_linear.py — weight_quantize /
weight_dequantize / weight_only_linear / llm_int8_linear).

TPU-native: the dequant (int8 -> bf16 multiply-by-scale) is expressed inline
so XLA fuses it into the matmul's operand load; there is no separate
dequantize kernel.  llm_int8's outlier decomposition uses a static-shape
mask (where) instead of gather so the program stays fully tileable.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.dispatch import def_op


@def_op("weight_quantize")
def weight_quantize(x, algo="weight_only_int8", group_size=-1):
    """Per-out-channel symmetric quantization of a [in, out] weight.

    Returns (quantized int8 weight [in, out], scale [out]).
    """
    if algo not in ("weight_only_int8", "llm.int8", "weight_only_int4"):
        raise ValueError(f"unsupported algo: {algo}")
    bits = 4 if algo == "weight_only_int4" else 8
    bnt = float((1 << (bits - 1)) - 1)
    absmax = jnp.max(jnp.abs(x), axis=0)
    scale = jnp.maximum(absmax, 1e-9) / bnt
    q = jnp.clip(jnp.round(x / scale), -bnt, bnt).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


@def_op("weight_dequantize")
def weight_dequantize(x, scale, algo="weight_only_int8", out_dtype="float32"):
    return (x.astype(out_dtype) * scale.astype(out_dtype)).astype(out_dtype)


@def_op("weight_only_linear")
def weight_only_linear(x, weight, weight_scale=None, bias=None,
                       weight_dtype="int8", arch=None, group_size=-1):
    """y = x @ dequant(weight) + bias, weight stored int8 [in, out].

    On TPU the int8 matmul runs through the Pallas weight-only kernel
    (ops/pallas/quant_matmul.py): weight tiles stream from HBM as int8
    and dequantize in VMEM, realizing the bandwidth saving the format
    exists for.  Elsewhere (and for int4) the inline-dequant XLA path."""
    if (weight_dtype == "int8" and weight.dtype == jnp.int8
            and weight_scale is not None and group_size == -1):
        from ...ops.pallas.quant_matmul import weight_only_matmul
        y = weight_only_matmul(x.reshape(-1, x.shape[-1]), weight,
                               weight_scale)
        y = y.reshape(*x.shape[:-1], weight.shape[-1])
    else:
        w = weight.astype(x.dtype)
        if weight_scale is not None:
            w = w * weight_scale.astype(x.dtype)
        y = jnp.matmul(x, w)
    if bias is not None:
        y = y + bias
    return y


@def_op("llm_int8_linear")
def llm_int8_linear(x, weight, weight_scale=None, bias=None, threshold=6.0):
    """LLM.int8(): activation feature dims with |x| > threshold (outliers)
    run in floating point; the rest are dynamically quantized per row and go
    through an int8 x int8 -> int32 matmul (2x MXU rate on TPU).  Outlier
    selection uses a static-shape mask (where), not gather, so the program
    stays fully tileable."""
    absmax = jnp.max(jnp.abs(x), axis=tuple(range(x.ndim - 1)))
    outlier = absmax > threshold                       # [in]
    x_regular = jnp.where(outlier, 0, x)
    x_outlier = jnp.where(outlier, x, 0)

    # regular path: dynamic per-row activation quantization + int8 matmul
    row_absmax = jnp.max(jnp.abs(x_regular), axis=-1, keepdims=True)
    xs = jnp.maximum(row_absmax, 1e-9) / 127.0
    xq = jnp.clip(jnp.round(x_regular / xs), -127, 127).astype(jnp.int8)
    acc = jnp.matmul(xq, weight, preferred_element_type=jnp.int32)
    wscale = (weight_scale.astype(x.dtype) if weight_scale is not None
              else jnp.ones((weight.shape[-1],), x.dtype))
    y = acc.astype(x.dtype) * xs.astype(x.dtype) * wscale

    # outlier path: full-precision matmul against the dequantized weight
    w_fp = weight.astype(x.dtype) * wscale
    y = y + jnp.matmul(x_outlier, w_fp)
    if bias is not None:
        y = y + bias
    return y
