"""nn.utils (reference: python/paddle/nn/utils/ — weight_norm_hook.py,
spectral_norm_hook.py, clip_grad_norm_.py, clip_grad_value_.py,
transform_parameters.py).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ...framework.tensor import Tensor, Parameter, wrap_array
from ...framework.tape import no_grad
from ... import tensor as T

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters"]


def weight_norm(layer, name="weight", dim=0):
    """reference: nn.utils.weight_norm — reparameterize ``name`` as
    g * v/||v||, recomputed before every forward via a pre-hook.
    ``dim=None`` uses one scalar norm over the whole tensor; negative
    dims count from the end."""
    w = getattr(layer, name)
    if dim is not None:
        dim = dim % w.ndim
    # reduction axes: everything but `dim` (all axes when dim is None)
    axes = [i for i in range(w.ndim) if i != dim]
    g = Parameter(jnp.sqrt(jnp.sum(w._data * w._data, axis=tuple(axes),
                                   keepdims=True)))
    v = Parameter(jnp.asarray(w._data))
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    # demote the original to a plain attribute recomputed per call
    del layer._parameters[name]

    def _recompute(layer_, *args):
        # TAPE-AWARE recompute (tensor ops, not raw jnp): the forward must
        # see a weight whose grad flows back into weight_g / weight_v
        vv = getattr(layer_, name + "_v")
        gg = getattr(layer_, name + "_g")
        norm = T.sqrt(T.sum(vv * vv, axis=axes, keepdim=True))
        setattr(layer_, name, gg * vv / (norm + 1e-12))

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_handle = (handle, name, axes)
    _recompute(layer)
    return layer


def remove_weight_norm(layer, name="weight"):
    """reference: nn.utils.remove_weight_norm."""
    handle, nm, axes = layer._weight_norm_handle
    handle.remove()
    v = getattr(layer, nm + "_v")
    g = getattr(layer, nm + "_g")
    norm = jnp.sqrt(jnp.sum(v._data * v._data, axis=tuple(axes),
                            keepdims=True))
    w = Parameter(g._data * v._data / (norm + 1e-12))
    del layer._parameters[nm + "_v"]
    del layer._parameters[nm + "_g"]
    layer.add_parameter(nm, w)
    del layer._weight_norm_handle
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """reference: nn.utils.spectral_norm — normalize ``name`` by its
    largest singular value (power iteration per forward)."""
    w = getattr(layer, name)
    if dim is None:
        dim = 0
    mat = jnp.moveaxis(w._data, dim, 0).reshape(w.shape[dim], -1)
    rng = np.random.default_rng(0)
    u = jnp.asarray(rng.normal(size=(mat.shape[0],)), mat.dtype)
    v = jnp.asarray(rng.normal(size=(mat.shape[1],)), mat.dtype)
    orig = Parameter(jnp.asarray(w._data))
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]
    state = {"u": u / jnp.linalg.norm(u), "v": v / jnp.linalg.norm(v)}

    def _recompute(layer_, *args):
        w_param = getattr(layer_, name + "_orig")
        ww = w_param._data
        m = jnp.moveaxis(ww, dim, 0).reshape(ww.shape[dim], -1)
        # power iteration on raw arrays — u/v carry no gradient (torch
        # semantics: they are buffers)
        u_, v_ = state["u"], state["v"]
        for _ in range(n_power_iterations):
            v_ = m.T @ u_
            v_ = v_ / (jnp.linalg.norm(v_) + eps)
            u_ = m @ v_
            u_ = u_ / (jnp.linalg.norm(u_) + eps)
        state["u"], state["v"] = u_, v_
        # sigma through TAPE-AWARE ops so grads reach weight_orig
        uT = wrap_array(u_)
        vT = wrap_array(v_)
        m_param = T.reshape(T.moveaxis(w_param, dim, 0),
                            [ww.shape[dim], -1])
        sigma = T.matmul(T.matmul(uT, m_param), vT)
        setattr(layer_, name, w_param / sigma)

    handle = layer.register_forward_pre_hook(_recompute)
    layer._spectral_norm_handle = (handle, name)
    _recompute(layer)
    return layer


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """reference: nn.utils.clip_grad_norm_ — clip IN PLACE, return the
    total norm."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return wrap_array(jnp.asarray(0.0))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._data))
                                   for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._data.astype(jnp.float32)) ** norm_type)
             for g in grads])) ** (1.0 / norm_type)
    if error_if_nonfinite and not bool(jnp.isfinite(total)):
        raise RuntimeError(
            f"gradient norm is non-finite ({float(total)}); set "
            f"error_if_nonfinite=False to clip anyway")
    scale = jnp.minimum(max_norm / (total + 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = (p.grad._data * scale).astype(p.grad._data.dtype)
    return wrap_array(total)


def clip_grad_value_(parameters, clip_value):
    """reference: nn.utils.clip_grad_value_."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    cv = float(clip_value)
    for p in parameters:
        if p.grad is not None:
            p.grad._data = jnp.clip(p.grad._data, -cv, cv)
    return parameters


def parameters_to_vector(parameters, name=None):
    """reference: nn.utils.parameters_to_vector — flatten+concat."""
    return wrap_array(jnp.concatenate(
        [p._data.reshape(-1) for p in parameters]))


def vector_to_parameters(vec, parameters, name=None):
    """reference: nn.utils.vector_to_parameters — scatter a flat vector
    back into the parameter tensors (in place)."""
    off = 0
    data = vec._data if isinstance(vec, Tensor) else jnp.asarray(vec)
    with no_grad():
        for p in parameters:
            n = int(np.prod(p.shape)) if p.ndim else 1
            p._data = data[off:off + n].reshape(p.shape).astype(
                p._data.dtype)
            off += n
    return parameters
