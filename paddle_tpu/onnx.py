"""Model interchange export (reference: python/paddle/onnx/export.py —
a paddle2onnx wrapper).

TPU-native: the portable interchange format on the XLA stack is StableHLO
(versioned, stable serialization), not ONNX — ``export`` emits the same
shape-polymorphic StableHLO artifact as ``paddle_tpu.jit.save`` and can be
loaded by any StableHLO consumer (or ``paddle_tpu.jit.load`` /
``paddle_tpu.inference``).  Direct ONNX emission is NOT implemented:
``format='onnx'`` always raises NotImplementedError pointing at the
StableHLO path (converting between the two graph dialects is out of scope;
ONNX consumers should ingest StableHLO via onnx-mlir or serve the StableHLO
artifact directly).
"""
from __future__ import annotations

from . import jit as _jit

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9,
           format="stablehlo", **configs):
    if format == "stablehlo":
        _jit.save(layer, path, input_spec=input_spec)
        return path + ".stablehlo"
    if format == "onnx":
        raise NotImplementedError(
            "direct ONNX emission is not implemented; export StableHLO "
            "(the default) — it is the portable interchange format on the "
            "XLA stack and any StableHLO consumer (incl. onnx-mlir "
            "pipelines) can ingest it")
    raise ValueError(f"unknown export format: {format}")
