"""Model interchange export (reference: python/paddle/onnx/export.py —
a paddle2onnx wrapper).

Two formats:

* ``format='stablehlo'`` (default) — the portable interchange format on
  the XLA stack; same shape-polymorphic artifact as ``jit.save``, loadable
  by any StableHLO consumer (or ``jit.load`` / ``paddle_tpu.inference``).
* ``format='onnx'`` — direct ONNX emission (``onnx_export``): the model
  is traced to jaxpr primitives and each primitive maps to ONNX ops
  (opset 13), weights become initializers.  Covers the mapped primitive
  subset (MLPs, conv nets, attention math without custom-kernel calls);
  an unmapped primitive raises with its name.
"""
from __future__ import annotations

from . import jit as _jit

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=13,
           format="stablehlo", example_inputs=None, **configs):
    if format == "stablehlo":
        _jit.save(layer, path, input_spec=input_spec)
        return path + ".stablehlo"
    if format == "onnx":
        from .onnx_export import export_onnx
        return export_onnx(layer, path, input_spec=input_spec,
                           example_inputs=example_inputs,
                           opset_version=opset_version)
    raise ValueError(f"unknown export format: {format}")
