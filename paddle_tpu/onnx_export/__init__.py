"""Direct ONNX emission from a traced jaxpr (SURVEY §2 #85; reference:
python/paddle/onnx/export.py — a paddle2onnx wrapper over the Program).

The TPU-native trick that makes this tractable: models are traced to
jaxpr PRIMITIVES first, so only the ~30 primitives below need ONNX
mappings — every composite (softmax, gelu, layernorm, attention math)
decomposes into them during tracing instead of needing its own
converter.  Weights become initializers; the file is stock ONNX
(ir_version 8, opset 13) serialized through a protoc-compiled subset of
the public onnx.proto schema (onnx_subset.proto — field numbers match
the published spec, so `onnx.load` and any ONNX runtime can read it).

Scope: inference graphs over the mapped primitives (MLPs, conv nets,
attention blocks without custom-kernel calls).  An unmapped primitive
raises with its name — nothing is silently dropped.
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from . import onnx_subset_pb2 as OP

_DTYPE = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}


def _elem_type(dtype) -> int:
    name = np.dtype(dtype).name if "bfloat16" not in str(dtype) \
        else "bfloat16"
    try:
        return _DTYPE[name]
    except KeyError:
        raise NotImplementedError(f"ONNX export: dtype {dtype}")


def _tensor_proto(name: str, arr: np.ndarray) -> "OP.TensorProto":
    t = OP.TensorProto()
    t.name = name
    t.dims.extend(int(d) for d in arr.shape)
    if str(arr.dtype) == "bfloat16":
        # ONNX BFLOAT16 raw encoding: little-endian uint16 truncation
        arr = np.asarray(arr, dtype=np.float32)
        bits = (arr.view(np.uint32) >> 16).astype(np.uint16)
        t.data_type = 16
        t.raw_data = bits.tobytes()
        return t
    t.data_type = _elem_type(arr.dtype)
    t.raw_data = np.ascontiguousarray(arr).tobytes()
    return t


class _Graph:
    """Accumulates nodes/initializers while walking the jaxpr."""

    def __init__(self):
        self.g = OP.GraphProto()
        self.g.name = "paddle_tpu"
        self._n = 0
        self._const_cache: Dict[Any, str] = {}

    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def node(self, op_type: str, inputs: Sequence[str], n_out: int = 1,
             **attrs) -> List[str]:
        nd = self.g.node.add()
        nd.op_type = op_type
        nd.name = self.fresh(op_type.lower())
        nd.input.extend(inputs)
        outs = [self.fresh(op_type.lower()) for _ in range(n_out)]
        nd.output.extend(outs)
        for k, v in attrs.items():
            a = nd.attribute.add()
            a.name = k
            if isinstance(v, float):
                a.type = OP.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, bool) or isinstance(v, (int, np.integer)):
                a.type = OP.AttributeProto.INT
                a.i = int(v)
            elif isinstance(v, str):
                a.type = OP.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (int, np.integer)) for x in v):
                a.type = OP.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
            elif isinstance(v, (list, tuple)):
                a.type = OP.AttributeProto.FLOATS
                a.floats.extend(float(x) for x in v)
            else:
                raise NotImplementedError(f"attr {k}={v!r}")
        return outs

    def const(self, arr: np.ndarray, hint="const") -> str:
        key = (arr.dtype.str, arr.shape, arr.tobytes())
        if key in self._const_cache:
            return self._const_cache[key]
        name = self.fresh(hint)
        self.g.initializer.append(_tensor_proto(name, arr))
        self._const_cache[key] = name
        return name

    def value_info(self, coll, name: str, shape, dtype):
        vi = coll.add()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = _elem_type(dtype)
        for d in shape:
            tt.shape.dim.add().dim_value = int(d)


def _np(x):
    return np.asarray(x)


# --------------------------------------------------------------------------
# primitive -> ONNX emitters.  Each takes (graph, eqn, in_names) and
# returns the list of output names.
# --------------------------------------------------------------------------
_ELEMENTWISE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "rem": "Mod",
    "neg": "Neg", "exp": "Exp", "log": "Log", "tanh": "Tanh",
    "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign", "floor": "Floor",
    "ceil": "Ceil", "erf": "Erf", "logistic": "Sigmoid",
    "sin": "Sin", "cos": "Cos",
    "not": "Not", "and": "And", "or": "Or",
}
_COMPARE = {"gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
            "le": "LessOrEqual", "eq": "Equal", "ne": "Equal"}


def _emit(g: _Graph, eqn, ins: List[str]) -> List[str]:
    p = eqn.primitive.name
    params = eqn.params
    aval = eqn.outvars[0].aval

    if p in ("stop_gradient", "copy", "device_put"):
        return [g.node("Identity", ins)[0]]
    if p == "convert_element_type":
        return [g.node("Cast", ins,
                       to=_elem_type(params["new_dtype"]))[0]]
    if p in _COMPARE:
        out = g.node(_COMPARE[p], ins)[0]
        if p == "ne":
            out = g.node("Not", [out])[0]
        return [out]
    if p in _ELEMENTWISE:
        return [g.node(_ELEMENTWISE[p], ins)[0]]
    if p == "rsqrt":
        return [g.node("Reciprocal", [g.node("Sqrt", ins)[0]])[0]]
    if p == "erfc":                     # 1 - erf(x)
        one = g.const(np.asarray(1.0, np.dtype(aval.dtype)), "one")
        return [g.node("Sub", [one, g.node("Erf", ins)[0]])[0]]
    if p == "erf_inv":
        raise NotImplementedError("ONNX export: primitive 'erf_inv'")
    if p == "integer_pow":
        y = params["y"]
        e = g.const(np.asarray(float(y), np.float32), "pow")
        return [g.node("Pow", [ins[0], e])[0]]
    if p == "square":
        return [g.node("Mul", [ins[0], ins[0]])[0]]
    if p == "select_n":
        # select_n(pred, case0, case1): pred True -> case1
        if len(ins) != 3:
            raise NotImplementedError(
                "ONNX export: select_n with more than 2 cases")
        return [g.node("Where", [ins[0], ins[2], ins[1]])[0]]
    if p == "reshape" or p == "squeeze" or p == "expand_dims":
        shp = g.const(np.asarray(aval.shape, np.int64), "shape")
        return [g.node("Reshape", [ins[0], shp])[0]]
    if p == "transpose":
        return [g.node("Transpose", ins,
                       perm=list(params["permutation"]))[0]]
    if p == "broadcast_in_dim":
        shape = list(aval.shape)
        bdims = list(params["broadcast_dimensions"])
        in_aval = eqn.invars[0].aval
        # insert size-1 dims so rank matches, then Expand
        inter = [1] * len(shape)
        for src, dst in enumerate(bdims):
            inter[dst] = in_aval.shape[src]
        cur = ins[0]
        if tuple(inter) != tuple(in_aval.shape):
            shp = g.const(np.asarray(inter, np.int64), "shape")
            cur = g.node("Reshape", [cur, shp])[0]
        if tuple(inter) != tuple(shape):
            shp = g.const(np.asarray(shape, np.int64), "shape")
            cur = g.node("Expand", [cur, shp])[0]
        return [cur]
    if p == "concatenate":
        return [g.node("Concat", ins, axis=int(params["dimension"]))[0]]
    if p == "slice":
        starts = list(params["start_indices"])
        ends = list(params["limit_indices"])
        axes = list(range(len(starts)))
        steps = list(params["strides"] or [1] * len(starts))
        return [g.node("Slice", [
            ins[0],
            g.const(np.asarray(starts, np.int64), "starts"),
            g.const(np.asarray(ends, np.int64), "ends"),
            g.const(np.asarray(axes, np.int64), "axes"),
            g.const(np.asarray(steps, np.int64), "steps")])[0]]
    if p == "dynamic_slice":
        # starts ride as scalar operands (constant-folded at export when
        # literal — the rope-table slice case); sizes are static params.
        # JAX CLAMPS out-of-range starts into [0, dim - size] so the
        # output always keeps slice_sizes — reproduce that with
        # Max(0, Min(starts, dims - sizes)) before the Slice, or the
        # exported graph shrinks at the boundary where JAX shifts.
        sizes = list(params["slice_sizes"])
        dims = list(eqn.invars[0].aval.shape)
        starts = g.node("Concat", [
            g.node("Reshape", [g.node("Cast", [s], to=7)[0],
                               g.const(np.asarray([1], np.int64),
                                       "shape")])[0]
            for s in ins[1:]], axis=0)[0]
        hi = g.const(np.asarray([d - s for d, s in zip(dims, sizes)],
                                np.int64), "maxstart")
        zero = g.const(np.zeros(len(sizes), np.int64), "zero")
        starts = g.node("Max", [g.node("Min", [starts, hi])[0], zero])[0]
        ends = g.node("Add", [starts,
                              g.const(np.asarray(sizes, np.int64),
                                      "sizes")])[0]
        axes = g.const(np.asarray(range(len(sizes)), np.int64), "axes")
        return [g.node("Slice", [ins[0], starts, ends, axes])[0]]
    if p == "dynamic_update_slice":
        raise NotImplementedError(
            "ONNX export: primitive 'dynamic_update_slice'")
    if p == "cumsum":
        axis = g.const(np.asarray(params["axis"], np.int64), "axis")
        return [g.node("CumSum", [ins[0], axis],
                       reverse=int(params.get("reverse", False)))[0]]
    if p == "rev":
        dims = list(params["dimensions"])
        in_shape = eqn.invars[0].aval.shape
        return [g.node("Slice", [
            ins[0],
            g.const(np.asarray([in_shape[d] - 1 for d in dims],
                               np.int64), "starts"),
            g.const(np.asarray([-(in_shape[d] + 1) for d in dims],
                               np.int64), "ends"),
            g.const(np.asarray(dims, np.int64), "axes"),
            g.const(np.asarray([-1] * len(dims), np.int64), "steps")])[0]]
    if p == "pad":
        lo, hi, interior = zip(*params["padding_config"])
        if any(i != 0 for i in interior):
            raise NotImplementedError("interior padding")
        if any(x < 0 for x in lo) or any(x < 0 for x in hi):
            raise NotImplementedError("negative padding")
        pads = g.const(np.asarray(list(lo) + list(hi), np.int64), "pads")
        return [g.node("Pad", [ins[0], pads, ins[1]])[0]]
    if p in ("reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
             "reduce_and", "reduce_or", "argmax", "argmin"):
        axes = list(params["axes"])
        if p == "reduce_sum":
            ax = g.const(np.asarray(axes, np.int64), "axes")
            return [g.node("ReduceSum", [ins[0], ax], keepdims=0)[0]]
        if p in ("argmax", "argmin"):
            (axis,) = axes
            out = g.node("ArgMax" if p == "argmax" else "ArgMin",
                         [ins[0]], axis=int(axis), keepdims=0)[0]
            want = _elem_type(aval.dtype)
            if want != 7:               # ArgMax emits int64
                out = g.node("Cast", [out], to=want)[0]
            return [out]
        op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
              "reduce_prod": "ReduceProd"}.get(p)
        if op is None:
            raise NotImplementedError(f"ONNX export: primitive {p}")
        return [g.node(op, [ins[0]], axes=axes, keepdims=0)[0]]
    if p == "gather":
        dn = params["dimension_numbers"]
        op_aval = eqn.invars[0].aval
        ss = tuple(params["slice_sizes"])
        if (tuple(dn.collapsed_slice_dims) == (0,)
                and tuple(dn.start_index_map) == (0,)
                and ss[0] == 1 and ss[1:] == tuple(op_aval.shape[1:])):
            # the embedding-lookup pattern: weight[ids] along axis 0
            idx_aval = eqn.invars[1].aval
            shp = g.const(np.asarray(idx_aval.shape[:-1], np.int64),
                          "shape")
            idx = g.node("Reshape", [ins[1], shp])[0]
            idx = g.node("Cast", [idx], to=7)[0]
            return [g.node("Gather", [ins[0], idx], axis=0)[0]]
        raise NotImplementedError(
            "ONNX export: general gather (only axis-0 embedding lookup "
            "is mapped)")
    if p == "dot_general":
        return _emit_dot(g, eqn, ins)
    if p == "conv_general_dilated":
        return _emit_conv(g, eqn, ins)
    if p == "reduce_window_max":
        return _emit_maxpool(g, eqn, ins)
    if p == "iota":
        # constant-fold: iota is static
        shape, dim = params["shape"], params["dimension"]
        arr = np.reshape(
            np.broadcast_to(
                np.arange(shape[dim]).reshape(
                    [-1 if i == dim else 1 for i in range(len(shape))]),
                shape),
            shape).astype(np.dtype(params["dtype"]))
        return [g.const(arr, "iota")]
    if p in ("custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
             "remat", "checkpoint", "closed_call", "core_call", "pjit",
             "jit"):
        sub = (params.get("call_jaxpr") or params.get("jaxpr")
               or params.get("fun_jaxpr"))
        if sub is None:
            raise NotImplementedError(f"ONNX export: call primitive {p} "
                                      "without an inlinable jaxpr")
        closed = sub if hasattr(sub, "jaxpr") else None
        inner = closed.jaxpr if closed else sub
        consts = closed.consts if closed else []
        if p in ("custom_jvp_call", "custom_vjp_call"):
            # primal function args only (no tangent plumbing at trace)
            n = len(inner.invars)
            ins = ins[-n:] if len(ins) >= n else ins
        return _walk(g, inner, consts, ins)
    raise NotImplementedError(f"ONNX export: primitive '{p}' is not in "
                              "the mapped subset")


def _emit_dot(g: _Graph, eqn, ins):
    ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    aval = eqn.outvars[0].aval
    ln, rn = ins
    if len(lc) != 1 or len(rc) != 1:
        raise NotImplementedError("dot_general with multiple contractions")
    lc, rc = lc[0], rc[0]
    # canonicalize to numpy-matmul form: batch dims leading and matching,
    # contraction = lhs last / rhs second-to-last
    lfree = [d for d in range(lhs.ndim) if d not in lb and d != lc]
    rfree = [d for d in range(rhs.ndim) if d not in rb and d != rc]
    lperm = list(lb) + lfree + [lc]
    if lperm != list(range(lhs.ndim)):
        ln = g.node("Transpose", [ln], perm=lperm)[0]
    rperm = list(rb) + [rc] + rfree
    if rperm != list(range(rhs.ndim)):
        rn = g.node("Transpose", [rn], perm=rperm)[0]
    # MatMul broadcasts batch dims from the RIGHT, so with explicit batch
    # dims each side must carry exactly one free dim — collapse extras
    # (Reshape around the MatMul) or the exported graph mis-broadcasts
    K = lhs.shape[lc]
    bshape = [lhs.shape[d] for d in lb]
    lf = [lhs.shape[d] for d in lfree]
    rf = [rhs.shape[d] for d in rfree]
    # MatMul's numpy-style broadcasting only matches dot_general when
    # each side carries exactly one free dim (rank-2 rhs with no batch
    # is the one safe exception, subsumed below by collapsing anyway)
    need_reshape = len(lf) != 1 or len(rf) != 1
    if need_reshape:
        m = int(np.prod(lf)) if lf else 1
        n = int(np.prod(rf)) if rf else 1
        shp = g.const(np.asarray(bshape + [m, K], np.int64), "shape")
        ln = g.node("Reshape", [ln, shp])[0]
        shp = g.const(np.asarray(bshape + [K, n], np.int64), "shape")
        rn = g.node("Reshape", [rn, shp])[0]
    out = g.node("MatMul", [ln, rn])[0]
    if need_reshape:
        shp = g.const(np.asarray(aval.shape, np.int64), "shape")
        out = g.node("Reshape", [out, shp])[0]
    return [out]


def _emit_conv(g: _Graph, eqn, ins):
    p = eqn.params
    dn = p["dimension_numbers"]
    # NCHW / OIHW / NCHW only (the framework's conv layout)
    spatial = len(p["window_strides"])
    want_lhs = (0, 1) + tuple(range(2, 2 + spatial))
    if (tuple(dn.lhs_spec) != want_lhs or tuple(dn.out_spec) != want_lhs
            or tuple(dn.rhs_spec) != want_lhs):
        raise NotImplementedError(
            f"conv layout {dn} (only NCHW/OIHW supported)")
    lo_hi = p["padding"]
    pads = [x[0] for x in lo_hi] + [x[1] for x in lo_hi]
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("transposed conv (lhs dilation)")
    return [g.node(
        "Conv", ins,
        strides=list(p["window_strides"]),
        pads=pads,
        dilations=list(p["rhs_dilation"]),
        group=int(p["feature_group_count"]))[0]]


def _emit_maxpool(g: _Graph, eqn, ins):
    p = eqn.params
    wd = list(p["window_dimensions"])
    ws = list(p["window_strides"])
    pad = list(p["padding"])
    if wd[0] != 1 or wd[1] != 1 or ws[0] != 1 or ws[1] != 1:
        raise NotImplementedError(
            "ONNX export: reduce_window_max pooling over batch/channel "
            "dims (window or stride != 1 outside spatial dims)")
    if any(x != (0, 0) for x in pad[:2]):
        raise NotImplementedError(
            "ONNX export: reduce_window_max padding on batch/channel")
    for key in ("base_dilation", "window_dilation"):
        if any(d != 1 for d in p.get(key) or []):
            raise NotImplementedError(
                f"ONNX export: reduce_window_max {key} != 1")
    pads = [x[0] for x in pad[2:]] + [x[1] for x in pad[2:]]
    return [g.node("MaxPool", ins, kernel_shape=wd[2:],
                   strides=ws[2:], pads=pads)[0]]


def _live_eqns(jaxpr):
    """Dead-code elimination: equations whose outputs never reach the
    jaxpr outputs are skipped entirely (e.g. RNG-key folds left behind
    by eval-mode paths) — their consts are then never materialized."""
    from jax.extend.core import Literal

    live = {v for v in jaxpr.outvars if not isinstance(v, Literal)}
    keep = []
    for eqn in reversed(jaxpr.eqns):
        if any(v in live for v in eqn.outvars):
            keep.append(eqn)
            for v in eqn.invars:
                if not isinstance(v, Literal):
                    live.add(v)
    keep.reverse()
    return keep


def _walk(g: _Graph, jaxpr, consts, in_names: List[str]) -> List[str]:
    """Emit nodes for one (sub)jaxpr; returns its output names."""
    from jax.extend.core import Literal

    env: Dict[Any, str] = {}
    for var, name in zip(jaxpr.invars, in_names):
        env[var] = name
    const_map = dict(zip(jaxpr.constvars, consts))

    def read(v):
        if isinstance(v, Literal):
            return g.const(np.asarray(v.val), "lit")
        if v not in env and v in const_map:
            # lazily materialized: dead consts (e.g. PRNG keys behind
            # DCE'd random ops) never need a numpy conversion
            env[v] = g.const(_np(const_map[v]), "const")
        return env[v]

    for eqn in _live_eqns(jaxpr):
        ins = [read(v) for v in eqn.invars]
        outs = _emit(g, eqn, ins)
        for var, name in zip(eqn.outvars, outs):
            env[var] = name
    return [read(v) for v in jaxpr.outvars]


def export_onnx(layer, path: str, input_spec=None, example_inputs=None,
                opset_version: int = 13) -> str:
    """Trace ``layer``'s forward to a jaxpr and serialize it as ONNX.

    ``example_inputs``: concrete Tensors/arrays (preferred), or
    ``input_spec``: a list of InputSpec-likes with .shape/.dtype.
    Returns the written path (``path`` + '.onnx' unless already given).
    """
    if not 13 <= int(opset_version) <= 17:
        # the emitted op forms (ReduceSum axes-as-input, ReduceMax
        # axes-as-attribute, GreaterOrEqual, ...) are exactly the
        # opset-13..17 shapes; stamping any other version would produce
        # a self-inconsistent file that runtimes reject at load
        raise ValueError(
            f"opset_version {opset_version} unsupported: the exporter "
            "emits opset 13-17 op forms")
    import jax
    import jax.numpy as jnp
    from ..framework.tape import no_grad
    from ..framework.tensor import Tensor, wrap_array

    if example_inputs is None:
        if input_spec is None:
            raise ValueError("provide example_inputs or input_spec")
        example_inputs = [
            wrap_array(jnp.zeros(
                [1 if (d is None or int(d) < 0) else int(d)
                 for d in s.shape],
                getattr(s, "dtype", "float32") or "float32"))
            for s in input_spec]
    example_inputs = [x if isinstance(x, Tensor) else wrap_array(
        jnp.asarray(x)) for x in example_inputs]

    params = [p for _, p in layer.named_parameters()]
    pnames = [n for n, _ in layer.named_parameters()]

    def fn(param_arrays, *input_arrays):
        saved = [p._data for p in params]
        try:
            for p, a in zip(params, param_arrays):
                p._data = a
            with no_grad():
                out = layer(*[wrap_array(a) for a in input_arrays])
            outs = out if isinstance(out, (tuple, list)) else [out]
            return [o._data if isinstance(o, Tensor) else o for o in outs]
        finally:
            for p, s in zip(params, saved):
                p._data = s

    was_training = getattr(layer, "training", False)
    if hasattr(layer, "eval"):
        layer.eval()                       # inference graph (no dropout)
    try:
        closed = jax.make_jaxpr(fn)(
            [p._data for p in params],
            *[x._data for x in example_inputs])
    finally:
        if was_training and hasattr(layer, "train"):
            layer.train()

    g = _Graph()
    # jaxpr invars = flattened [param_arrays..., inputs...]
    n_params = len(params)
    in_names = []
    for i, var in enumerate(closed.jaxpr.invars):
        if i < n_params:
            name = pnames[i].replace(".", "/")
            g.g.initializer.append(
                _tensor_proto(name, _np(params[i]._data)))
        else:
            name = f"input_{i - n_params}"
            av = var.aval
            g.value_info(g.g.input, name, av.shape, av.dtype)
        in_names.append(name)
    out_names = _walk(g, closed.jaxpr, closed.consts, in_names)
    for i, (name, var) in enumerate(zip(out_names, closed.jaxpr.outvars)):
        av = var.aval
        # graph outputs must be named node outputs, not initializers
        final = g.node("Identity", [name])[0]
        g.value_info(g.g.output, final, av.shape, av.dtype)

    m = OP.ModelProto()
    m.ir_version = 8
    m.producer_name = "paddle_tpu"
    m.producer_version = "0.1"
    op = m.opset_import.add()
    op.domain = ""
    op.version = opset_version
    m.graph.CopyFrom(g.g)
    if not path.endswith(".onnx"):
        path = path + ".onnx"
    with open(path, "wb") as f:
        f.write(m.SerializeToString())
    return path
