"""Custom ops (Pallas kernels + composites)."""
