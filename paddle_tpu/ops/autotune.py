"""Kernel autotune: runtime implementation selection + persistent cache.

Capability parity with the reference's kernel autotune
(reference: paddle/phi/kernels/autotune/ — cache.cc keyed per op+shape,
auto_tune_base.h timing candidate kernels, switch_autotune.cc).

TPU-native: candidates are whole implementations (Pallas kernel vs XLA
fusion) rather than cudnn algorithms.  On an *eager* call with concrete
arrays the candidates are timed once per shape key and the winner is cached
(in-memory + JSON on disk).  Under tracing (jit) timing is impossible, so a
cached winner is used when present, else the caller's analytical heuristic.
"""
from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional

_CACHE_PATH = os.environ.get(
    "PADDLE_TPU_AUTOTUNE_CACHE",
    os.path.expanduser("~/.cache/paddle_tpu/autotune.json"))

_lock = threading.Lock()
_cache: Optional[Dict[str, str]] = None
_enabled = True
_device_tag: Optional[str] = None


def _get_device_tag() -> str:
    """Winners are only valid for the device they were measured on."""
    global _device_tag
    if _device_tag is None:
        try:
            import jax
            d = jax.devices()[0]
            _device_tag = f"{d.platform}/{getattr(d, 'device_kind', '?')}"
        except Exception:
            _device_tag = "unknown"
    return _device_tag


def _full_key(key: str) -> str:
    return f"{_get_device_tag()}::{key}"


def _load() -> Dict[str, str]:
    global _cache
    if _cache is None:
        try:
            with open(_CACHE_PATH) as f:
                _cache = json.load(f)
        except (OSError, json.JSONDecodeError):
            _cache = {}
    return _cache


def _persist() -> None:
    try:
        os.makedirs(os.path.dirname(_CACHE_PATH), exist_ok=True)
        tmp = _CACHE_PATH + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(_cache, f, indent=1, sort_keys=True)
        os.replace(tmp, _CACHE_PATH)
    except OSError:
        pass


def set_enabled(on: bool) -> None:
    global _enabled
    _enabled = bool(on)


# switch through the framework flag registry (reference:
# paddle/phi/kernels/autotune/switch_autotune.cc + FLAGS_use_autotune);
# env FLAGS_use_autotune is ingested by define_flag, set_flags updates live
from ..framework.flags import define_flag, get_flag  # noqa: E402

define_flag("use_autotune", True,
            "measure and cache kernel-implementation choices",
            on_change=set_enabled)
_enabled = bool(get_flag("use_autotune"))


def lookup(key: str) -> Optional[str]:
    with _lock:
        return _load().get(_full_key(key))


def record(key: str, winner: str) -> None:
    with _lock:
        _load()[_full_key(key)] = winner
        _persist()


def _time_one(fn: Callable, repeats: int = 3) -> float:
    import jax
    out = fn()                       # compile + warm
    jax.block_until_ready(out)
    best = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def select(key: str, arr, candidates: Dict[str, Callable],
           default: str, tpu_only: bool = True) -> str:
    """Shared impl-selection policy (attention / rmsnorm / rope):
    under tracing use the cached winner (or default, never measure);
    eagerly on TPU measure-and-cache; elsewhere the default."""
    import jax
    if isinstance(arr, jax.core.Tracer):
        return lookup(key) or default
    if tpu_only and jax.default_backend() != "tpu":
        return default
    return autotune(key, candidates, default)


def autotune(key: str, candidates: Dict[str, Callable],
             default: str) -> str:
    """Winner for ``key``: cached if known; measured now if enabled and all
    candidates are runnable; else ``default``."""
    if not _enabled:
        return default
    hit = lookup(key)
    if hit in candidates:
        return hit
    timings = {}
    for name, fn in candidates.items():
        try:
            timings[name] = _time_one(fn)
        except Exception:
            continue             # candidate not runnable for this shape
    if not timings:
        return default
    winner = min(timings, key=timings.get)
    record(key, winner)
    return winner
