"""Pallas TPU kernels (flash attention, rmsnorm, rope, ring attention)."""
