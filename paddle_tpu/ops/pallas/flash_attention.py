"""Flash attention for TPU: Pallas forward kernel + memory-efficient backward.

Capability parity with the reference's flash-attention stack
(reference: paddle/phi/kernels/gpu/flash_attn_kernel.cu wrapping flashattn
v2/v3 via paddle/phi/backends/dynload/flashattn.cc; Python API
python/paddle/nn/functional/flash_attention.py:364).

TPU-native design (see /opt/skills/guides/pallas_guide.md):
  - forward: online-softmax tiled kernel; grid (batch, heads, q_blocks,
    kv_blocks) with the kv axis 'arbitrary' (sequential) so m/l/acc scratch
    carries across kv tiles; MXU matmuls via dot_general with
    preferred_element_type=f32; causal tiles beyond the diagonal are skipped
    with @pl.when.
  - backward: blockwise XLA recomputation from the saved logsumexp (the
    flash-attention-2 backward formulation) under lax.scan — O(seq * block)
    memory without a second hand-written kernel.
  - off-TPU (CPU tests) the same math runs as a plain XLA reference.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_MASK_VALUE = -0.7 * float(jnp.finfo(jnp.float32).max)


# --------------------------------------------------------------- reference
def mha_reference(q, k, v, causal=False, scale=None, bias=None):
    """Plain XLA attention (correctness baseline + CPU fallback).

    Layout: q/k/v = (batch, heads, seq, head_dim); supports GQA
    (k/v heads dividing q heads).
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    kv_heads = k.shape[1]
    q_heads = q.shape[1]
    if kv_heads != q_heads:
        rep = q_heads // kv_heads
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if bias is not None:
        s = s + bias
    if causal:
        sq, sk = q.shape[2], k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


# ------------------------------------------------------------------ kernel
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_kv,
                kv_seq_len, causal_offset):
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # For causal attention, tiles strictly above the (bottom-right-aligned,
    # offset = sk - sq) diagonal contribute nothing; predicate them off
    # (grid still visits, compute is skipped).
    if causal:
        run = (q_idx * block_q + block_q - 1 + causal_offset
               >= kv_idx * block_kv)
    else:
        run = True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                       # (block_q, d)
        k = k_ref[0, 0]                       # (block_kv, d)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_idx * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 0)
            cols = kv_idx * block_kv + lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(rows + causal_offset >= cols, s,
                          DEFAULT_MASK_VALUE)
        # mask kv padding (kv_seq_len may be < padded length)
        cols = kv_idx * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        s = jnp.where(cols < kv_seq_len, s, DEFAULT_MASK_VALUE)

        m_prev = m_scr[:, :1]                 # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)
        l_next = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_next, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_next, l_scr.shape)

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_scr[:] + jnp.log(l_safe)).astype(jnp.float32)


def _ceil_to(x, m):
    return (x + m - 1) // m * m


def flash_attention_forward(q, k, v, causal=False, scale=None,
                            block_q=512, block_kv=512, interpret=False):
    """Pallas forward. Layout (b, h, s, d). Returns (out, lse)."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    b, h, sq, d = q.shape
    kv_h, sk = k.shape[1], k.shape[2]
    block_q = min(block_q, _ceil_to(sq, 128))
    block_kv = min(block_kv, _ceil_to(sk, 128))
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_kv)
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))

    grid = (b, h, sq_p // block_q, sk_p // block_kv)
    group = h // kv_h

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, kv_seq_len=sk, causal_offset=sk - sq)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, qi, ki: (b_, h_ // group, ki, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, qi, ki: (b_, h_ // group, ki, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq_p, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k, v)
    return out[:, :, :sq, :], lse[:, :, :sq, 0]


# ------------------------------------------------- backward (Pallas, TPU)
def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_kv, q_seq_len, causal_offset):
    """FA2 backward, dk/dv: grid (b, h, kv_blocks, q_blocks); the q axis is
    sequential so dk/dv accumulate in VMEM scratch across q tiles
    (reference: flash_attn_grad_kernel.cu dk/dv pass)."""
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    if causal:   # tiles strictly above the diagonal contribute nothing
        run = (q_idx * block_q + block_q - 1 + causal_offset
               >= kv_idx * block_kv)
    else:
        run = True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]                        # (block_q, d)
        k = k_ref[0, 0]                        # (block_kv, d)
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)  # (block_q, d)
        lse = lse_ref[0, 0][:, :1]             # (block_q, 1)
        delta = delta_ref[0, 0][:, :1]

        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        rows = q_idx * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        cols = kv_idx * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        mask = rows < q_seq_len                # q padding rows contribute 0
        if causal:
            mask = mask & (rows + causal_offset >= cols)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        # dv += p^T @ do
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        # dp = do @ v^T ; ds = p * (dp - delta) * scale
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        # dk += ds^T @ q
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_idx == n_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dq_ref, dq_scr, *, scale, causal, block_q, block_kv,
                   kv_seq_len, causal_offset):
    """FA2 backward, dq: grid (b, h, q_blocks, kv_blocks); the kv axis is
    sequential so dq accumulates in VMEM scratch across kv tiles."""
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    if causal:
        run = (q_idx * block_q + block_q - 1 + causal_offset
               >= kv_idx * block_kv)
    else:
        run = True

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]

        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        rows = q_idx * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 0)
        cols = kv_idx * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        mask = cols < kv_seq_len               # kv padding cols
        if causal:
            mask = mask & (rows + causal_offset >= cols)
        p = jnp.where(mask, jnp.exp(s - lse), 0.0)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] = dq_scr[:] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


def _expand_to_128(x, pad_to):
    """(b, h, s) -> (b, h, pad_to, 128) f32 — the lane-broadcast layout the
    TPU kernels read scalars-per-row from (same trick as the fwd lse out).

    Deliberate 128x HBM cost for these two per-row scalars: jax's own
    production TPU flash kernel broadcasts l/m/di identically before its
    backward pallas_calls (jax/experimental/pallas/ops/tpu/
    flash_attention.py _flash_attention_bwd_dkv) — lane-1 blocks don't
    tile; the arrays are transient within the backward step."""
    b, h, s = x.shape
    x = x.astype(jnp.float32)
    if pad_to != s:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, pad_to - s)))
    return jnp.broadcast_to(x[..., None], (b, h, pad_to, 128))


def flash_attention_backward(q, k, v, out, lse, do, causal, scale,
                             block_q=512, block_kv=512, interpret=False):
    """Pallas FA2 backward (dq, dk, dv) in layout (b, h, s, d).

    Two kernels: dk/dv with the q axis sequential, dq with the kv axis
    sequential.  GQA folds the head group AFTER the kernels (sum over the
    repeated q-heads), like the XLA fallback.
    """
    b, h, sq, d = q.shape
    kv_h, sk = k.shape[1], k.shape[2]
    group = h // kv_h
    k_full = jnp.repeat(k, group, axis=1) if group != 1 else k
    v_full = jnp.repeat(v, group, axis=1) if group != 1 else v

    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)                           # (b, h, sq)

    block_q = min(block_q, _ceil_to(sq, 128))
    block_kv = min(block_kv, _ceil_to(sk, 128))
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_kv)
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
        do = jnp.pad(do, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k_full = jnp.pad(k_full, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v_full = jnp.pad(v_full, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    lse128 = _expand_to_128(lse, sq_p)
    delta128 = _expand_to_128(delta, sq_p)

    n_q, n_kv = sq_p // block_q, sk_p // block_kv

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, q_seq_len=sq, causal_offset=sk - sq)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(b, h, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, ki, qi: (b_, h_, qi, 0)),   # q
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, ki, qi: (b_, h_, ki, 0)),   # k
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, ki, qi: (b_, h_, ki, 0)),   # v
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, ki, qi: (b_, h_, qi, 0)),   # do
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h_, ki, qi: (b_, h_, qi, 0)),   # lse
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h_, ki, qi: (b_, h_, qi, 0)),   # delta
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, ki, qi: (b_, h_, ki, 0)),
        ],
        out_shape=[
            # f32 so the GQA group sum below accumulates in full precision
            # (the XLA fallback sums the group in f32 too)
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk_p, d), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_kv, d), jnp.float32),
            pltpu.VMEM((block_kv, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k_full, v_full, do, lse128, delta128)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=block_q,
        block_kv=block_kv, kv_seq_len=sk, causal_offset=sk - sq)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(b, h, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),   # q
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, qi, ki: (b_, h_, ki, 0)),   # k
            pl.BlockSpec((1, 1, block_kv, d),
                         lambda b_, h_, qi, ki: (b_, h_, ki, 0)),   # v
            pl.BlockSpec((1, 1, block_q, d),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),   # do
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),   # lse
            pl.BlockSpec((1, 1, block_q, 128),
                         lambda b_, h_, qi, ki: (b_, h_, qi, 0)),   # delta
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, d),
                               lambda b_, h_, qi, ki: (b_, h_, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(q, k_full, v_full, do, lse128, delta128)

    dq = dq[:, :, :sq, :]
    dk = dk[:, :, :sk, :]
    dv = dv[:, :, :sk, :]
    if group != 1:
        dk = dk.reshape(b, kv_h, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, kv_h, group, sk, d).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ------------------------------------------------ backward (XLA fallback)
def _bwd_blockwise(q, k, v, out, lse, do, causal, scale, block_kv=1024):
    """Flash-attention-2 backward via lax.scan over kv blocks (pure XLA)."""
    b, h, sq, d = q.shape
    kv_h, sk = k.shape[1], k.shape[2]
    group = h // kv_h
    if group != 1:
        k_full = jnp.repeat(k, group, axis=1)
        v_full = jnp.repeat(v, group, axis=1)
    else:
        k_full, v_full = k, v

    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    delta = jnp.sum(out.astype(jnp.float32) * dof, axis=-1)  # (b,h,sq)

    block_kv = min(block_kv, sk)
    sk_p = _ceil_to(sk, block_kv)
    if sk_p != sk:
        k_full = jnp.pad(k_full, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v_full = jnp.pad(v_full, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    n_blocks = sk_p // block_kv

    k_blocks = k_full.reshape(b, h, n_blocks, block_kv, d).transpose(2, 0, 1, 3, 4)
    v_blocks = v_full.reshape(b, h, n_blocks, block_kv, d).transpose(2, 0, 1, 3, 4)

    rows = jnp.arange(sq)[:, None]

    def body(dq_acc, inp):
        blk_idx, kb, vb = inp
        cols = blk_idx * block_kv + jnp.arange(block_kv)[None, :]
        s = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32)) * scale
        mask = cols < sk
        if causal:   # bottom-right aligned (offset sk - sq), like the fwd
            mask = mask & (rows + (sk - sq) >= cols)
        p = jnp.where(mask, jnp.exp(s - lse[..., None]), 0.0)
        dv_b = jnp.einsum("bhqk,bhqd->bhkd", p, dof)
        dp = jnp.einsum("bhqd,bhkd->bhqk", dof, vb.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq_acc = dq_acc + jnp.einsum("bhqk,bhkd->bhqd", ds,
                                     kb.astype(jnp.float32))
        dk_b = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        return dq_acc, (dk_b, dv_b)

    dq0 = jnp.zeros_like(qf)
    dq, (dk_blocks, dv_blocks) = lax.scan(
        body, dq0, (jnp.arange(n_blocks), k_blocks, v_blocks))
    dk = dk_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, sk_p, d)[:, :, :sk]
    dv = dv_blocks.transpose(1, 2, 0, 3, 4).reshape(b, h, sk_p, d)[:, :, :sk]
    if group != 1:
        dk = dk.reshape(b, kv_h, group, sk, d).sum(axis=2)
        dv = dv.reshape(b, kv_h, group, sk, d).sum(axis=2)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ----------------------------------------------------------- public entry
def _use_pallas():
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def flash_attention_bhsd(q, k, v, causal=False, scale=None):
    """Flash attention, layout (batch, heads, seq, head_dim)."""
    out, _ = _fwd_impl(q, k, v, causal, scale)
    return out


def _fwd_impl(q, k, v, causal, scale):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas():
        out, lse = flash_attention_forward(q, k, v, causal, scale)
        return out, lse
    # XLA fallback (CPU tests): compute lse explicitly.
    kv_heads, q_heads = k.shape[1], q.shape[1]
    kk, vv = k, v
    if kv_heads != q_heads:
        rep = q_heads // kv_heads
        kk = jnp.repeat(k, rep, axis=1)
        vv = jnp.repeat(v, rep, axis=1)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kk,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = q.shape[2], kk.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        s = jnp.where(mask, s, DEFAULT_MASK_VALUE)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(vv.dtype), vv)
    return out.astype(q.dtype), lse


def _fa_fwd(q, k, v, causal, scale):
    out, lse = _fwd_impl(q, k, v, causal, scale)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, res, do):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if _use_pallas():
        dq, dk, dv = flash_attention_backward(q, k, v, out, lse, do,
                                              causal, scale)
    else:
        dq, dk, dv = _bwd_blockwise(q, k, v, out, lse, do, causal, scale)
    return dq, dk, dv


flash_attention_bhsd.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_bshd(q, k, v, causal=False, scale=None):
    """Paddle layout (batch, seq, heads, head_dim) — the reference API layout
    (python/paddle/nn/functional/flash_attention.py)."""
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out = flash_attention_bhsd(qt, kt, vt, causal, scale)
    return jnp.swapaxes(out, 1, 2)
