"""FlashMask attention: sparse-mask flash kernels (the 'splash' slot of
SURVEY §7's kernel list).

Capability parity: the reference's flashmask_attention (PaddlePaddle 3.0
headline; python/paddle/nn/functional/flash_attention.py flashmask_attention)
— attention masks encoded as per-column row INTERVALS
(startend_row_indices, O(seq) memory) instead of a dense O(seq^2) bias:
column j of the score matrix is masked for rows in [start_j, end_j)
(1 col: [start, Sq); 2 cols: [start, end); 4 cols: two bands).

TPU-native design:
  - forward + FA2 backward Pallas kernels modeled on flash_attention.py,
    with the interval tensor streamed per kv tile in an (ncol, seq)
    layout (lane-aligned blocks);
  - REAL flop sparsity: a per-(b, h, q_block, kv_block) skip table is
    precomputed in XLA from the intervals and scalar-prefetched; fully
    masked tiles are predicated off, so banded masks (sliding window,
    causal document masks) cost near-linear compute like the splash
    kernels — the dense-bias path pays O(s^2) regardless;
  - off-TPU the dense reference in nn/functional/attention.py stays the
    fallback and the correctness oracle (interpret mode runs the
    kernels on CPU in tests).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import DEFAULT_MASK_VALUE, _ceil_to

#: Flip to True in CPU tests to run through the Pallas interpreter.
_INTERPRET = False


def _keep_mask(rows, cols_base, se, ncol, sq, causal, block_kv):
    """KEEP mask (True = attend) for one tile.  rows: (block_q, 1) global
    row ids; se: (ncol, block_kv) intervals for this kv tile."""
    def band(lo, hi):
        return (rows >= lo[None, :]) & (rows < hi[None, :])

    if ncol == 1:
        masked = band(se[0], jnp.full_like(se[0], sq))
    elif ncol == 2:
        masked = band(se[0], se[1])
    else:
        masked = band(se[0], se[1]) | band(se[2], se[3])
    if causal:
        cols = cols_base + lax.broadcasted_iota(
            jnp.int32, masked.shape, 1)
        masked = masked | (rows < cols)
    return ~masked


def _tile(skip_ref, se_ref, q_idx, kv_idx, *, ncol, sq, causal, block_q,
          block_kv):
    b_ = pl.program_id(0)
    h_ = pl.program_id(1)
    run = skip_ref[b_, h_, q_idx, kv_idx] == 0
    rows = q_idx * block_q + lax.broadcasted_iota(
        jnp.int32, (block_q, 1), 0)
    keep = _keep_mask(rows, kv_idx * block_kv, se_ref[0], ncol, sq,
                      causal, block_kv)
    return run, rows, keep


def _fwd_kernel(skip_ref, q_ref, k_ref, v_ref, se_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q,
                block_kv, kv_seq_len, q_seq_len, ncol):
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kv_idx == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, -jnp.inf)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    run, rows, keep = _tile(skip_ref, se_ref, q_idx, kv_idx, ncol=ncol,
                            sq=q_seq_len, causal=causal, block_q=block_q,
                            block_kv=block_kv)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        cols = kv_idx * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        kp = keep & (cols < kv_seq_len)
        s = jnp.where(kp, s, DEFAULT_MASK_VALUE)
        m_prev = m_scr[:, :1]
        m_next = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.where(kp, jnp.exp(s - m_next), 0.0)
        l_scr[:] = jnp.broadcast_to(
            alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True),
            l_scr.shape)
        acc_scr[:] = acc_scr[:] * alpha + lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[:] = jnp.broadcast_to(m_next, m_scr.shape)

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        # fully-masked rows keep lse = -big so exp(s - lse) stays 0 in bwd
        lse_ref[0, 0] = jnp.where(
            l > 0.0, m_scr[:] + jnp.log(l_safe),
            DEFAULT_MASK_VALUE).astype(jnp.float32)


def _bwd_dkv_kernel(skip_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                    delta_ref, se_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    scale, causal, block_q, block_kv, q_seq_len, ncol):
    kv_idx = pl.program_id(2)
    q_idx = pl.program_id(3)
    n_q = pl.num_programs(3)

    @pl.when(q_idx == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run, rows, keep = _tile(skip_ref, se_ref, q_idx, kv_idx, ncol=ncol,
                            sq=q_seq_len, causal=causal, block_q=block_q,
                            block_kv=block_kv)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        kp = keep & (rows < q_seq_len)
        p = jnp.where(kp, jnp.exp(s - lse), 0.0)
        dv_scr[:] = dv_scr[:] + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_scr[:] = dk_scr[:] + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(q_idx == n_q - 1)
    def _finalize():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _bwd_dq_kernel(skip_ref, q_ref, k_ref, v_ref, do_ref, lse_ref,
                   delta_ref, se_ref, dq_ref, dq_scr, *, scale, causal,
                   block_q, block_kv, kv_seq_len, q_seq_len, ncol):
    q_idx = pl.program_id(2)
    kv_idx = pl.program_id(3)
    n_kv = pl.num_programs(3)

    @pl.when(kv_idx == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run, rows, keep = _tile(skip_ref, se_ref, q_idx, kv_idx, ncol=ncol,
                            sq=q_seq_len, causal=causal, block_q=block_q,
                            block_kv=block_kv)

    @pl.when(run)
    def _compute():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        cols = kv_idx * block_kv + lax.broadcasted_iota(
            jnp.int32, (block_q, block_kv), 1)
        kp = keep & (cols < kv_seq_len)
        p = jnp.where(kp, jnp.exp(s - lse), 0.0)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_scr[:] = dq_scr[:] + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kv_idx == n_kv - 1)
    def _finalize():
        dq_ref[0, 0] = dq_scr[:].astype(dq_ref.dtype)


# ------------------------------------------------------------- skip table
def _skip_table(se_bh, ncol, sq, block_q, block_kv, n_q, n_kv, causal,
                b, h, hm):
    """(b, h, n_q, n_kv) int32: 1 where the tile is FULLY masked (the
    kernels predicate it off).  Conservative for the 4-col case (a tile
    covered only by the UNION of both bands still runs)."""
    bh = se_bh.shape[0]
    sqz = se_bh.reshape(bh, ncol, n_kv, block_kv)
    smax = jnp.max(sqz, axis=3)                     # (bh, ncol, n_kv)
    smin = jnp.min(sqz, axis=3)
    q0 = jnp.arange(n_q)[:, None] * block_q         # (n_q, 1)
    q1 = jnp.minimum(q0 + block_q, sq)

    def covered(lo_max, hi_min):
        return (lo_max[:, None, :] <= q0[None]) & \
               (hi_min[:, None, :] >= q1[None])

    if ncol == 1:
        full = covered(smax[:, 0], jnp.full_like(smin[:, 0], sq))
    elif ncol == 2:
        full = covered(smax[:, 0], smin[:, 1])
    else:
        full = covered(smax[:, 0], smin[:, 1]) | \
               covered(smax[:, 2], smin[:, 3])
    full = full.reshape(b, hm, n_q, n_kv)
    full = jnp.broadcast_to(full[:, :, None].repeat(h // hm, axis=2)
                            .reshape(b, h, n_q, n_kv), (b, h, n_q, n_kv))
    if causal:
        k0 = jnp.arange(n_kv)[None, None, None, :] * block_kv
        above = q1.reshape(1, 1, n_q, 1) <= k0
        full = full | above
    return full.astype(jnp.int32)


def _prep(q, k, v, startend_row_indices, block_q, block_kv, causal):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    hm = startend_row_indices.shape[1]
    ncol = startend_row_indices.shape[-1]
    if ncol not in (1, 2, 4):
        raise ValueError(f"startend_row_indices last dim must be 1, 2 or "
                         f"4, got {ncol}")
    if h % hm != 0:
        raise ValueError(f"mask heads ({hm}) must divide q heads ({h})")
    block_q = min(block_q, _ceil_to(sq, 128))
    block_kv = min(block_kv, _ceil_to(sk, 128))
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_kv)
    pads = {}
    if sq_p != sq:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, sq_p - sq), (0, 0)))
    if sk_p != sk:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, sk_p - sk), (0, 0)))
    se = jnp.swapaxes(startend_row_indices, 2, 3).astype(jnp.int32)
    se = se.reshape(b * hm, ncol, sk)
    if sk_p != sk:
        # padded cols: start=sq, end=sq -> empty band; the kv_seq_len
        # in-kernel mask excludes them anyway
        se = jnp.pad(se, ((0, 0), (0, 0), (0, sk_p - sk)),
                     constant_values=sq)
    n_q, n_kv = sq_p // block_q, sk_p // block_kv
    skip = _skip_table(se, ncol, sq, block_q, block_kv, n_q, n_kv, causal,
                       b, h, hm)
    # expand the per-mask-head intervals to per-q-head blocks
    se_h = se.reshape(b, hm, ncol, sk_p)
    se_h = jnp.repeat(se_h, h // hm, axis=1).reshape(b * h, ncol, sk_p)
    meta = dict(b=b, h=h, sq=sq, sk=sk, d=d, ncol=ncol,
                block_q=block_q, block_kv=block_kv, sq_p=sq_p, sk_p=sk_p,
                n_q=n_q, n_kv=n_kv)
    return q, k, v, se_h, skip, meta


def _se_spec(meta):
    # (b*h, ncol, sk_p) indexed per (b, h, kv tile)
    h = meta["h"]
    return pl.BlockSpec(
        (1, meta["ncol"], meta["block_kv"]),
        lambda b_, h_, qi, ki, skip_r: (b_ * h + h_, 0, ki))


def flashmask_attention_forward(q, k, v, startend_row_indices,
                                causal=False, scale=None, block_q=512,
                                block_kv=512, interpret=None):
    """Layout (b, h, s, d); returns (out, lse)."""
    if interpret is None:
        interpret = _INTERPRET
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    q, k, v, se, skip, meta = _prep(q, k, v, startend_row_indices,
                                    block_q, block_kv, causal)
    m = meta
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, block_q=m["block_q"],
        block_kv=m["block_kv"], kv_seq_len=m["sk"], q_seq_len=m["sq"],
        ncol=m["ncol"])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m["b"], m["h"], m["n_q"], m["n_kv"]),
        in_specs=[
            pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, ki, 0)),
            _se_spec(m),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_q"], 128),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((m["block_q"], 128), jnp.float32),
            pltpu.VMEM((m["block_q"], 128), jnp.float32),
            pltpu.VMEM((m["block_q"], m["d"]), jnp.float32),
        ],
    )
    out, lse = pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((m["b"], m["h"], m["sq_p"], m["d"]),
                                 q.dtype),
            jax.ShapeDtypeStruct((m["b"], m["h"], m["sq_p"], 128),
                                 jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(skip, q, k, v, se)
    return out[:, :, :m["sq"], :], lse[:, :, :m["sq"], 0]


def flashmask_attention_backward(q, k, v, out, lse, do,
                                 startend_row_indices, causal=False,
                                 scale=None, block_q=512, block_kv=512,
                                 interpret=None):
    """FA2 backward under the interval mask; returns (dq, dk, dv)."""
    from .flash_attention import _expand_to_128

    if interpret is None:
        interpret = _INTERPRET
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)
    qp, kp_, vp, se, skip, meta = _prep(q, k, v, startend_row_indices,
                                        block_q, block_kv, causal)
    m = meta
    if m["sq_p"] != m["sq"]:
        do = jnp.pad(do, ((0, 0), (0, 0), (0, m["sq_p"] - m["sq"]),
                          (0, 0)))
    lse128 = _expand_to_128(lse, m["sq_p"])
    delta128 = _expand_to_128(delta, m["sq_p"])

    dkv_kernel = functools.partial(
        _bwd_dkv_kernel, scale=scale, causal=causal, block_q=m["block_q"],
        block_kv=m["block_kv"], q_seq_len=m["sq"], ncol=m["ncol"])
    dkv_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m["b"], m["h"], m["n_kv"], m["n_q"]),
        in_specs=[
            pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                         lambda b_, h_, ki, qi, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, ki, qi, s_: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, ki, qi, s_: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                         lambda b_, h_, ki, qi, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_q"], 128),
                         lambda b_, h_, ki, qi, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_q"], 128),
                         lambda b_, h_, ki, qi, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, m["ncol"], m["block_kv"]),
                         lambda b_, h_, ki, qi, s_:
                         (b_ * m["h"] + h_, 0, ki)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, ki, qi, s_: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, ki, qi, s_: (b_, h_, ki, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((m["block_kv"], m["d"]), jnp.float32),
            pltpu.VMEM((m["block_kv"], m["d"]), jnp.float32),
        ],
    )
    # the dkv grid iterates (kv, q) but _tile receives the caller's own
    # (q_idx, kv_idx) and indexes the table [b, h, q, kv] — no transpose
    dk, dv = pl.pallas_call(
        dkv_kernel, grid_spec=dkv_spec,
        out_shape=[
            jax.ShapeDtypeStruct((m["b"], m["h"], m["sk_p"], m["d"]),
                                 jnp.float32),
            jax.ShapeDtypeStruct((m["b"], m["h"], m["sk_p"], m["d"]),
                                 jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(skip, qp, kp_, vp, do, lse128, delta128, se)

    dq_kernel = functools.partial(
        _bwd_dq_kernel, scale=scale, causal=causal, block_q=m["block_q"],
        block_kv=m["block_kv"], kv_seq_len=m["sk"], q_seq_len=m["sq"],
        ncol=m["ncol"])
    dq_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(m["b"], m["h"], m["n_q"], m["n_kv"]),
        in_specs=[
            pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, m["block_kv"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, ki, 0)),
            pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_q"], 128),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
            pl.BlockSpec((1, 1, m["block_q"], 128),
                         lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
            _se_spec(m),
        ],
        out_specs=pl.BlockSpec((1, 1, m["block_q"], m["d"]),
                               lambda b_, h_, qi, ki, s_: (b_, h_, qi, 0)),
        scratch_shapes=[pltpu.VMEM((m["block_q"], m["d"]), jnp.float32)],
    )
    dq = pl.pallas_call(
        dq_kernel, grid_spec=dq_spec,
        out_shape=jax.ShapeDtypeStruct(
            (m["b"], m["h"], m["sq_p"], m["d"]), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary")),
        interpret=interpret,
    )(skip, qp, kp_, vp, do, lse128, delta128, se)

    dq = dq[:, :, :m["sq"]]
    dk = dk[:, :, :m["sk"]].astype(k.dtype)
    dv = dv[:, :, :m["sk"]].astype(v.dtype)
    return dq.astype(q.dtype), dk, dv


# ------------------------------------------------------------ public vjp
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def flashmask_attention_fused(q, k, v, startend_row_indices, causal=False,
                              scale=None):
    """Differentiable FlashMask attention, layout (b, h, s, d)."""
    out, _ = flashmask_attention_forward(q, k, v, startend_row_indices,
                                         causal, scale)
    return out


def _fm_fwd(q, k, v, se, causal, scale):
    out, lse = flashmask_attention_forward(q, k, v, se, causal, scale)
    return out, (q, k, v, se, out, lse)


def _fm_bwd(causal, scale, res, do):
    q, k, v, se, out, lse = res
    dq, dk, dv = flashmask_attention_backward(
        q, k, v, out, lse, do, se, causal, scale)
    return dq, dk, dv, jnp.zeros_like(se)


flashmask_attention_fused.defvjp(_fm_fwd, _fm_bwd)
