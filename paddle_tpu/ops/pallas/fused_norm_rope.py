"""Fused RMSNorm and rotary-embedding Pallas kernels.

Capability parity: the reference's fusion kernel family —
paddle/phi/kernels/fusion/gpu/fused_rope_{kernel,grad_kernel}.cu and the
rms_norm fusion (paddle/phi/kernels/gpu/rms_norm_kernel.cu), surfaced as
paddle.incubate.nn.functional.fused_rotary_position_embedding /
fused_rms_norm.

TPU-native role: XLA already fuses both chains well; these kernels exist
for the shapes where a single-pass VMEM-resident kernel beats the XLA
fusion (long rows, bf16), selected per shape by ops/autotune.py — the
same measured dispatch the flash-attention path uses.  Off-TPU the XLA
forms are the reference implementations the kernels are tested against
(interpret mode).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _ceil_to

#: Flip to True in CPU tests to run the kernels through the Pallas
#: interpreter (Mosaic only compiles on TPU).
_INTERPRET = False


# ----------------------------------------------------------------- rmsnorm
def _rms_kernel(x_ref, w_ref, o_ref, *, epsilon, hidden):
    x = x_ref[:].astype(jnp.float32)               # (block_rows, hidden)
    var = jnp.mean(jnp.square(x), axis=1, keepdims=True)
    y = x * lax.rsqrt(var + epsilon)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def rms_norm_pallas(x, weight, epsilon=1e-6, block_rows=256,
                    interpret=None):
    """Single-pass fused RMSNorm over the last dim.  x: (..., hidden)."""
    if interpret is None:
        interpret = _INTERPRET
    hidden = x.shape[-1]
    lead = x.shape[:-1]
    rows = 1
    for n in lead:
        rows *= n
    x2 = x.reshape(rows, hidden)
    block_rows = min(block_rows, _ceil_to(rows, 8))
    rows_p = _ceil_to(rows, block_rows)
    if rows_p != rows:
        x2 = jnp.pad(x2, ((0, rows_p - rows), (0, 0)))
    w2 = weight.reshape(1, hidden)

    out = pl.pallas_call(
        functools.partial(_rms_kernel, epsilon=epsilon, hidden=hidden),
        grid=(rows_p // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, hidden), lambda r: (r, 0)),
            pl.BlockSpec((1, hidden), lambda r: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, hidden), lambda r: (r, 0)),
        out_shape=jax.ShapeDtypeStruct((rows_p, hidden), x.dtype),
        interpret=interpret,
    )(x2, w2)
    return out[:rows].reshape(*lead, hidden)


def rms_norm_xla(x, weight, epsilon=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1,
                   keepdims=True)
    out = (x.astype(jnp.float32) * lax.rsqrt(var + epsilon)).astype(x.dtype)
    return out * weight if weight is not None else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_fused(x, weight, epsilon=1e-6):
    """Differentiable fused RMSNorm: Pallas forward on TPU, analytic
    XLA backward (a pallas_call has no transpose rule, so autodiff
    through the raw kernel would fail — same reason flash_attention
    wraps its kernels in custom_vjp)."""
    return rms_norm_pallas(x, weight, epsilon)


def _rms_fwd(x, weight, epsilon):
    return rms_norm_fused(x, weight, epsilon), (x, weight)


def _rms_bwd(epsilon, res, g):
    x, w = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = w.astype(jnp.float32)
    H = x.shape[-1]
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    r = lax.rsqrt(var + epsilon)
    gw = gf * wf
    # d/dx [x_i * r * w_i] : r*gw_i - (r^3 / H) * x_i * sum_j gw_j x_j
    dot = jnp.sum(gw * xf, axis=-1, keepdims=True)
    dx = (r * gw - (r ** 3 / H) * xf * dot).astype(x.dtype)
    axes = tuple(range(x.ndim - 1))
    dw = jnp.sum(gf * xf * r, axis=axes).astype(w.dtype)
    return dx, dw


rms_norm_fused.defvjp(_rms_fwd, _rms_bwd)


# -------------------------------------------------------------------- rope
def _rope_kernel(q_ref, k_ref, cos_ref, sin_ref, oq_ref, ok_ref, *, half):
    cos = cos_ref[:][:, None, :]                   # (block_s, 1, half)
    sin = sin_ref[:][:, None, :]

    def rot(ref, out):
        x = ref[0].astype(jnp.float32)             # (block_s, heads, d)
        x1 = x[..., :half]
        x2 = x[..., half:]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out[0] = jnp.concatenate([o1, o2], axis=-1).astype(out.dtype)

    rot(q_ref, oq_ref)
    rot(k_ref, ok_ref)


def fused_rope_pallas(q, k, cos, sin, block_s=512, interpret=None):
    """Rotate q and k in ONE kernel.  q: (b, s, h, d), k: (b, s, kvh, d);
    cos/sin: (s, d/2) already sliced to the position window."""
    if interpret is None:
        interpret = _INTERPRET
    b, s, h, d = q.shape
    kvh = k.shape[2]
    half = d // 2
    block_s = min(block_s, _ceil_to(s, 8))
    s_p = _ceil_to(s, block_s)
    if s_p != s:
        pad = ((0, 0), (0, s_p - s), (0, 0), (0, 0))
        q = jnp.pad(q, pad)
        k = jnp.pad(k, pad)
        cos = jnp.pad(cos, ((0, s_p - s), (0, 0)))
        sin = jnp.pad(sin, ((0, s_p - s), (0, 0)))
    cosf = cos.astype(jnp.float32)
    sinf = sin.astype(jnp.float32)

    oq, ok = pl.pallas_call(
        functools.partial(_rope_kernel, half=half),
        grid=(b, s_p // block_s),
        in_specs=[
            pl.BlockSpec((1, block_s, h, d), lambda b_, si: (b_, si, 0, 0)),
            pl.BlockSpec((1, block_s, kvh, d),
                         lambda b_, si: (b_, si, 0, 0)),
            pl.BlockSpec((block_s, half), lambda b_, si: (si, 0)),
            pl.BlockSpec((block_s, half), lambda b_, si: (si, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_s, h, d), lambda b_, si: (b_, si, 0, 0)),
            pl.BlockSpec((1, block_s, kvh, d),
                         lambda b_, si: (b_, si, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, s_p, h, d), q.dtype),
            jax.ShapeDtypeStruct((b, s_p, kvh, d), k.dtype),
        ],
        interpret=interpret,
    )(q, k, cosf, sinf)
    return oq[:, :s], ok[:, :s]


def fused_rope_xla(q, k, cos, sin):
    """XLA reference: same math, compiler-fused."""
    c = cos[None, :, None, :].astype(jnp.float32)
    si = sin[None, :, None, :].astype(jnp.float32)

    def rot(x):
        half = x.shape[-1] // 2
        x1 = x[..., :half].astype(jnp.float32)
        x2 = x[..., half:].astype(jnp.float32)
        return jnp.concatenate(
            [x1 * c - x2 * si, x2 * c + x1 * si], axis=-1).astype(x.dtype)

    return rot(q), rot(k)


@jax.custom_vjp
def fused_rope_fused(q, k, cos, sin):
    """Differentiable fused rope: Pallas forward, rotation-transpose
    backward (the adjoint of a rotation by theta is a rotation by -theta,
    so the backward reuses the SAME kernel with negated sin)."""
    return fused_rope_pallas(q, k, cos, sin)


def _rope_fwd(q, k, cos, sin):
    return fused_rope_fused(q, k, cos, sin), (q, k, cos, sin)


def _rope_bwd(res, g):
    q, k, cos, sin = res
    gq, gk = g
    dq, dk = fused_rope_pallas(gq, gk, cos, -sin)

    # true table cotangents (matching the XLA path's autodiff — tables
    # are usually frozen buffers, but a learned/scaled rope experiment
    # must not get silent zeros): with o1 = x1 c - x2 s, o2 = x2 c + x1 s,
    #   dc = Σ g1 x1 + g2 x2,   ds = Σ g2 x1 - g1 x2   (over batch, heads)
    def table_grads(x, gx):
        half = x.shape[-1] // 2
        x1 = x[..., :half].astype(jnp.float32)
        x2 = x[..., half:].astype(jnp.float32)
        g1 = gx[..., :half].astype(jnp.float32)
        g2 = gx[..., half:].astype(jnp.float32)
        dc = jnp.sum(g1 * x1 + g2 * x2, axis=(0, 2))
        ds = jnp.sum(g2 * x1 - g1 * x2, axis=(0, 2))
        return dc, ds

    dc_q, ds_q = table_grads(q, gq)
    dc_k, ds_k = table_grads(k, gk)
    return (dq, dk, (dc_q + dc_k).astype(cos.dtype),
            (ds_q + ds_k).astype(sin.dtype))


fused_rope_fused.defvjp(_rope_fwd, _rope_bwd)
