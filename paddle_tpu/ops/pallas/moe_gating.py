"""Fused MoE top-k gating Pallas kernels.

Capability parity: the gating half of the reference's fused MoE stack
(paddle/phi/kernels/fusion/gpu/fused_moe_kernel.cu top-k gating +
python/paddle/incubate/distributed/models/moe/gate/) — SURVEY §7 lists
"MoE dispatch, top-k gating" among the Pallas kernel targets.

Produces the ragged-routing metadata (expert id, capacity slot, keep
mask, raw combine weight per assignment) that moe_ragged_dispatch
consumes — softmax, argmax and capacity positions fused VMEM-resident
instead of ~6 XLA ops per round.

Slot-assignment order is ROUND-MAJOR over all tokens (every token's
round-0 choice takes a slot before any round-1 choice), exactly the
oracle's (gate._topk_routing) semantics — which matters because the
order decides WHICH assignments a full expert drops.  One pallas_call
per round (k is 1-3 in practice): the token-tile axis is sequential so
a VMEM scratch carries per-expert fill counts across tiles, and the
counts chain between rounds through a tiny (1, E) array; each round
re-derives its `remaining` mask from the gates by replaying the earlier
argmax rounds locally (cheaper than carrying a [T, E] mask).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import _ceil_to


def _argmax_rows(x):
    """Row-wise argmax as max + first-match index (reduce/compare/min
    only — Mosaic has no argmax primitive on every supported jax)."""
    E = x.shape[1]
    m = jnp.max(x, axis=1, keepdims=True)
    col = lax.broadcasted_iota(jnp.float32, x.shape, 1)
    # float reduce: Mosaic only lowers float reductions; E is far below
    # f32's exact-integer range
    return jnp.min(jnp.where(x == m, col, float(E)),
                   axis=1).astype(jnp.int32)


def _round_kernel(logits_ref, fill_in_ref, eidx_ref, pos_ref, keep_ref,
                  w_ref, fill_out_ref, gsum_ref, fill_scr, gsum_scr, *,
                  round_k, capacity, n_tokens, block_t):
    t_idx = pl.program_id(0)
    n_tiles = pl.num_programs(0)

    @pl.when(t_idx == 0)
    def _init():
        fill_scr[:] = fill_in_ref[:]
        gsum_scr[:] = jnp.zeros_like(gsum_scr)

    logits = logits_ref[:].astype(jnp.float32)       # (block_t, E)
    E = logits.shape[1]
    rows = t_idx * block_t + lax.broadcasted_iota(
        jnp.int32, (block_t, 1), 0)
    valid = rows < n_tokens                          # (block_t, 1)
    z = logits - jnp.max(logits, axis=1, keepdims=True)
    ez = jnp.exp(z)
    gates = ez / jnp.sum(ez, axis=1, keepdims=True)

    # replay rounds 0..round_k-1 to mask their choices (deterministic)
    remaining = gates
    for _ in range(round_k):
        prev = _argmax_rows(remaining)
        oh = (lax.broadcasted_iota(jnp.int32, (block_t, E), 1)
              == prev[:, None]).astype(jnp.float32)
        remaining = remaining * (1.0 - oh)

    idx = _argmax_rows(remaining)                    # (block_t,)
    # counts ride in f32 end to end (Mosaic lowers only float
    # reductions); exact up to 2^24 assignments, far beyond any tile
    onehot = (lax.broadcasted_iota(jnp.int32, (block_t, E), 1)
              == idx[:, None]).astype(jnp.float32)
    onehot = onehot * valid.astype(jnp.float32)      # pad rows place none
    fill = fill_scr[0]                               # (E,) carried
    # within-tile exclusive prefix count as a strictly-lower-triangular
    # matmul (Mosaic has no cumsum primitive; this rides the MXU)
    r_i = lax.broadcasted_iota(jnp.int32, (block_t, block_t), 0)
    c_i = lax.broadcasted_iota(jnp.int32, (block_t, block_t), 1)
    strict_tril = (c_i < r_i).astype(jnp.float32)
    prefix = lax.dot_general(
        strict_tril, onehot,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    pos = jnp.sum((prefix + fill[None, :].astype(jnp.float32)) * onehot,
                  axis=1).astype(jnp.int32)
    within = (pos < capacity) & valid[:, 0]
    gate_val = jnp.sum(gates * onehot, axis=1)
    eidx_ref[0] = idx.astype(jnp.int32)
    pos_ref[0] = pos.astype(jnp.int32)
    keep_ref[0] = within.astype(jnp.int32)
    w_ref[0] = gate_val * within.astype(jnp.float32)
    fill_scr[0] = fill + jnp.sum(onehot, axis=0).astype(jnp.int32)
    if round_k == 0:
        # per-expert sum of gate probabilities over valid tokens — the
        # l_aux ingredient; only round 0's is consumed, so later rounds
        # skip the accumulation entirely (round_k is trace-static)
        gsum_scr[0] = gsum_scr[0] + jnp.sum(
            gates * valid.astype(jnp.float32), axis=0)

    @pl.when(t_idx == n_tiles - 1)
    def _flush():
        fill_out_ref[:] = fill_scr[:]
        gsum_ref[:] = gsum_scr[:]


def topk_gating_pallas(logits, top_k, capacity, normalize,
                       block_t=256, interpret=False):
    """(eidx, pos, keep, w, l_aux): the _topk_routing contract, fused.

    logits: [T, E] float.  No GShard random-keep (the oracle handles
    that branch); callers fall back when random_keep is not None.
    """
    T, E = logits.shape
    block_t = min(block_t, _ceil_to(T, 128))
    T_p = _ceil_to(T, block_t)
    if T_p != T:
        logits = jnp.pad(logits, ((0, T_p - T), (0, 0)),
                         constant_values=-1e30)
    grid = (T_p // block_t,)
    row_spec = pl.BlockSpec((1, block_t), lambda t: (0, t))
    fill_spec = pl.BlockSpec((1, E), lambda t: (0, 0))

    fill = jnp.zeros((1, E), jnp.int32)
    fill0 = None
    gsum = None
    eidx_l, pos_l, keep_l, w_l = [], [], [], []
    for k in range(top_k):
        kernel = functools.partial(
            _round_kernel, round_k=k, capacity=capacity, n_tokens=T,
            block_t=block_t)
        e_k, p_k, kp_k, w_k, fill, gsum_k = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[pl.BlockSpec((block_t, E), lambda t: (t, 0)),
                      fill_spec],
            out_specs=[row_spec, row_spec, row_spec, row_spec, fill_spec,
                       fill_spec],
            out_shape=[
                jax.ShapeDtypeStruct((1, T_p), jnp.int32),
                jax.ShapeDtypeStruct((1, T_p), jnp.int32),
                jax.ShapeDtypeStruct((1, T_p), jnp.int32),
                jax.ShapeDtypeStruct((1, T_p), jnp.float32),
                jax.ShapeDtypeStruct((1, E), jnp.int32),
                jax.ShapeDtypeStruct((1, E), jnp.float32),
            ],
            scratch_shapes=[pltpu.VMEM((1, E), jnp.int32),
                            pltpu.VMEM((1, E), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
            interpret=interpret,
        )(logits, fill)
        if k == 0:
            fill0, gsum = fill, gsum_k
        eidx_l.append(e_k[0, :T])
        pos_l.append(p_k[0, :T])
        keep_l.append(kp_k[0, :T])
        w_l.append(w_k[0, :T])

    eidx = jnp.stack(eidx_l)
    pos = jnp.stack(pos_l)
    keep = jnp.stack(keep_l).astype(bool)
    w = jnp.stack(w_l)
    if normalize:
        w = w / jnp.maximum(jnp.sum(w, axis=0, keepdims=True), 1e-9)
    w = w.astype(logits.dtype)
    # l_aux (GShard balance loss over the top-1 assignment) from the
    # kernel's own byproducts — round-0 fill IS the per-expert top-1
    # count, gsum the per-expert gate-probability mass; no [T, E]
    # softmax or one-hot replay in the epilogue
    me = gsum[0] / T
    ce = fill0[0].astype(jnp.float32) / T
    l_aux = jnp.sum(me * ce) * E
    return eidx, pos, keep, w, l_aux
